"""Telemetry-fed autotuner: a persistent per-host measurement store that
closes the loop from measurement to dispatch (ROADMAP item 4).

Every ``auto`` decision in the package — engine selection for host arrays
(``core._choose_engine``), the segment-sum lowering
(``kernels._segment_sum_impl``), quantile sort-vs-select
(``kernels._quantile_impl_choice``), and the streaming slab/prefetch sizing
(``streaming.py`` / ``pipeline.stream_slabs``) — used to be a static
heuristic, while PR 4's telemetry already measured exactly the signals
needed to choose better. This module is the store those signals feed and the
decision point that consults it:

* **Measurement store** (:data:`_AUTOTUNE_CACHE`): observed GB/s per
  candidate, keyed by ``(op-family, platform, dtype, ngroups-band,
  nelems-band)``. Fed by four sources: one-time micro-sweeps at first
  decision (:func:`prime_reduce` — budgeted, so an instrumented test suite
  stays bounded), the bench harnesses' impl sweeps (``bench.py`` records its
  ``impl_sweep_gbps`` / ``quantile_gbps`` winners here), per-pass
  :class:`~flox_tpu.profiling.StreamReport` observations
  (:func:`observe_stream` — throughput and overlap fraction per prefetch
  depth and slab band), and seeding from the repo's committed hardware
  evidence (``BENCH_TPU_LAST.json`` / ``BENCH_HISTORY``, :func:`seed`).
* **Decisions** (:func:`decide`): with ``FLOX_TPU_AUTOTUNE=1`` an ``auto``
  policy returns the observed winner for the nearest measured band; without
  a record (or with the tuner off — the default) the existing heuristic
  runs unchanged, so dispatch is bit-identical to the pre-autotune tree.
  Off is *record-only*: observations still accrete (that is what makes the
  first enabled run informed), decisions never change.
* **Persistence**: atomic JSON-on-disk at ``OPTIONS["autotune_cache_path"]``
  (env ``FLOX_TPU_AUTOTUNE_CACHE_PATH``; ``None`` keeps the store
  in-process). A second process on the same host loads the file lazily at
  first consult and makes every measured decision without re-sweeping
  (``sweeps``/``cache_hits`` counters in :func:`decision_record` assert
  this). A corrupt or partial file falls back to heuristics with a warning
  — never an error on the hot path.
* **Trace safety**: decisions are consulted at trace time inside jitted
  programs, so :func:`decision_fingerprint` rides
  ``options.trace_fingerprint()`` — a record that flips a winner bumps the
  store version and invalidates exactly the compiled programs that baked
  the old choice in.
* **Regression sentinel** (:func:`regression_sentinel`): diffs a round's
  per-family GB/s against the store and the last ``BENCH_HISTORY`` round,
  flagging >15 % regressions in the emitted JSON (report-only in CI).

CLI: ``python -m flox_tpu.autotune report`` prints the store;
``python -m flox_tpu.autotune sentinel --current '{"fam": gbps}'`` runs the
sentinel standalone.

The in-memory store and its counters are registered in ``cache.clear_all``
(floxlint FLX008); clearing resets to the unloaded state, so the next
consult reloads from disk (or runs heuristics when no path is configured).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import warnings
from typing import Any, Callable, Iterable, Mapping

logger = logging.getLogger("flox_tpu.autotune")

__all__ = [
    "compare_families",
    "decide",
    "decision_fingerprint",
    "decision_record",
    "enabled",
    "load",
    "lookup",
    "make_key",
    "observe_stream",
    "pick_stream_batch_bytes",
    "pick_stream_prefetch",
    "prime_reduce",
    "record",
    "regression_sentinel",
    "save",
    "seed",
]

#: on-disk format version — a loader seeing another version discards the
#: file (with a warning) instead of misreading bands measured under
#: different key semantics
_FORMAT_VERSION = 1

#: the store: key string -> {"candidates": {name: {"gbps", "n"}}, "source"}.
#: Module-level mutable cache — registered in cache.clear_all (FLX008).
_AUTOTUNE_CACHE: dict[str, dict] = {}

#: process-local tuner state: lazy-load flag, sweep/hit counters, version.
#: A plain dict cleared by cache.clear_all; every read goes through .get()
#: with a default, so the cleared (empty) dict IS the reset state.
_AUTOTUNE_STATE: dict[str, Any] = {}

_LOCK = threading.RLock()

#: per-process ceiling on in-call micro-sweeps: an instrumented test suite
#: meeting hundreds of fresh (dtype, band) keys must stay bounded — keys
#: past the budget fall back to heuristics and measure nothing
_SWEEP_BUDGET = 16

#: micro-sweep workload bounds (elements along the reduced axis / kept rows)
_SWEEP_N_MAX = 8192
_SWEEP_ROWS = 8

#: engine-sweep workload cap: the numpy/jax crossover the sweep probes
#: lives in small-host-array territory, and a sweep this size says nothing
#: about bands beyond the engine tolerance (see :func:`prime_engine`)
_SWEEP_ENGINE_N_MAX = 65536

#: regression threshold for the sentinel: a family is flagged when its
#: GB/s drops below (1 - this) x the comparison point
_REGRESSION_THRESHOLD = 0.15

#: band-distance tolerance for nearest-band lookups, per family. Engine
#: crossover is sharply size-dependent (numpy wins only for small hosts
#: arrays), so its records must not stretch; kernel-lowering winners are
#: stable across decades of size, so seeds from bench-scale workloads may
#: serve interactive-scale calls. The "fused" family (fused-vs-sequential
#: multi-statistic dispatch, fed by bench.py's fused_sweep_gbps) rides the
#: stretchy default for the same reason.
_NEAREST_TOLERANCE = {"engine": 1}
_NEAREST_TOLERANCE_DEFAULT = 6

#: families whose winner is governed by the GROUP band: the highcard
#: dense-vs-sort crossover lives on the ngroups axis, so its nearest-band
#: match additionally bounds the group-band distance — a record swept at
#: the capped 2^20 universe must not decide for workloads on the other
#: side of the crossover. Families absent here keep the legacy behavior
#: (group band is a tiebreak only).
_NEAREST_TOLERANCE_GROUPS = {"highcard": 2}


def enabled() -> bool:
    """Whether autotuned dispatch is on (``OPTIONS["autotune"]``).

    Off (the default) is record-only: the store still accretes
    observations, decisions stay on the static heuristics."""
    from .options import OPTIONS

    return bool(OPTIONS["autotune"])


def cache_path() -> str | None:
    """The configured persistence path (``OPTIONS["autotune_cache_path"]``)."""
    from .options import OPTIONS

    path = OPTIONS["autotune_cache_path"]
    return None if path is None else str(path)


# ---------------------------------------------------------------------------
# key schema
# ---------------------------------------------------------------------------


def _platform() -> str:
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 — keying must never break dispatch
        return "unknown"


def _gband(ngroups: int) -> int:
    """Group-count band: log2 (0 for unknown/zero)."""
    return int(ngroups).bit_length() if ngroups > 0 else 0


def _eband(nelems: int) -> int:
    """Element-count band: log4 — coarse on purpose, so a test suite's shape
    variety maps to a bounded key population."""
    return (int(nelems).bit_length() + 1) // 2 if nelems > 0 else 0


def make_key(
    family: str,
    *,
    dtype: Any = None,
    ngroups: int = 0,
    nelems: int = 0,
    platform: str | None = None,
) -> str:
    """The store key: ``family|platform|dtype|g<band>|e<band>``."""
    plat = _platform() if platform is None else platform
    dt = "any" if dtype is None else str(dtype)
    return f"{family}|{plat}|{dt}|g{_gband(ngroups)}|e{_eband(nelems)}"


def _split_key(key: str) -> tuple[str, str, str, int, int] | None:
    parts = key.split("|")
    if len(parts) != 5:
        return None
    try:
        return parts[0], parts[1], parts[2], int(parts[3][1:]), int(parts[4][1:])
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# persistence
# ---------------------------------------------------------------------------


def _ensure_loaded() -> None:
    """Lazy one-time load from the configured path (plus history seeding).

    Runs once per process (per ``clear_all``); a missing file is the normal
    fresh-host case, a corrupt one warns and falls back to heuristics."""
    with _LOCK:
        if _AUTOTUNE_STATE.get("loaded"):
            return
        _AUTOTUNE_STATE["loaded"] = True
        path = cache_path()
        if path is not None:
            load(path)
        if enabled():
            # fold in the repo's committed hardware evidence so the first
            # enabled call is informed (platform-keyed, so a CPU process
            # never serves an on-chip seed and vice versa). Seeds land only
            # under keys without real observations — a disk store holding,
            # say, stream records must not suppress the quantile seed.
            seed()


def load(path: str) -> bool:
    """Merge a persisted store file into the in-memory store.

    Returns whether a valid file was read. Corrupt/partial/alien-version
    files warn and leave the store unchanged — the decision layer then runs
    the plain heuristics, which is always safe."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except FileNotFoundError:
        return False
    except (OSError, ValueError) as exc:
        warnings.warn(
            f"flox_tpu.autotune: cache file {path!r} is unreadable "
            f"({type(exc).__name__}: {exc}); falling back to heuristics",
            RuntimeWarning,
            stacklevel=2,
        )
        return False
    if not isinstance(payload, dict) or payload.get("version") != _FORMAT_VERSION:
        warnings.warn(
            f"flox_tpu.autotune: cache file {path!r} has unsupported format "
            f"{payload.get('version') if isinstance(payload, dict) else type(payload).__name__!r}; "
            "falling back to heuristics",
            RuntimeWarning,
            stacklevel=2,
        )
        return False
    records = payload.get("records")
    if not isinstance(records, dict):
        warnings.warn(
            f"flox_tpu.autotune: cache file {path!r} carries no record table; "
            "falling back to heuristics",
            RuntimeWarning,
            stacklevel=2,
        )
        return False
    merged = 0
    with _LOCK:
        for key, rec in records.items():
            if _split_key(key) is None or not isinstance(rec, dict):
                continue
            cands = rec.get("candidates")
            if not isinstance(cands, dict):
                continue
            clean = {
                str(name): {"gbps": float(c["gbps"]), "n": int(c.get("n", 1))}
                for name, c in cands.items()
                if isinstance(c, dict) and isinstance(c.get("gbps"), (int, float))
            }
            if not clean:
                continue
            # a loaded record wins over nothing but merges under any
            # same-key in-process observations (those are fresher)
            slot = _AUTOTUNE_CACHE.setdefault(
                key, {"candidates": {}, "source": str(rec.get("source", "disk"))}
            )
            for name, c in clean.items():
                slot["candidates"].setdefault(name, c)
            merged += 1
        if merged:
            _AUTOTUNE_STATE["version"] = _AUTOTUNE_STATE.get("version", 0) + 1
    logger.debug("autotune: loaded %d record(s) from %s", merged, path)
    return merged > 0


def save(path: str | None = None) -> str | None:
    """Atomically persist the store as JSON (tmp + rename).

    ``None`` uses the configured ``autotune_cache_path``; with neither, the
    save is a no-op returning ``None``."""
    path = cache_path() if path is None else str(path)
    if path is None:
        return None
    # merge-on-save: a record-only process may never have consulted the
    # store (so the lazy load never ran) — writing just its own records
    # would clobber every other process's persisted measurements. Folding
    # the file in first is safe: in-process observations win on key
    # collisions (load() is setdefault-merge), missing files are the
    # normal fresh-host case.
    load(path)
    with _LOCK:
        # deep-copy down to the candidate slots: json.dump runs outside the
        # lock, and a concurrent record() mutating a live candidates dict
        # mid-serialization would abort the save
        payload = {
            "version": _FORMAT_VERSION,
            "platform": _platform(),
            "records": {
                key: {
                    "candidates": {
                        name: dict(c) for name, c in rec["candidates"].items()
                    },
                    "source": rec.get("source", "observed"),
                }
                for key, rec in _AUTOTUNE_CACHE.items()
            },
        }
    parent = os.path.dirname(path)
    try:
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)  # a crash mid-write never truncates the store
    except OSError as exc:
        logger.warning("autotune: could not persist store to %s: %s", path, exc)
        return None
    return path


def _register_atexit() -> None:
    with _LOCK:  # RLock: safe whether or not the caller already holds it
        if _AUTOTUNE_STATE.get("atexit"):
            return
        _AUTOTUNE_STATE["atexit"] = True
    import atexit

    atexit.register(_save_at_exit)


def _save_at_exit() -> None:
    if _AUTOTUNE_CACHE and cache_path() is not None:
        save()


# ---------------------------------------------------------------------------
# recording + lookup
# ---------------------------------------------------------------------------


def record(
    family: str,
    candidate: str,
    gbps: float,
    *,
    dtype: Any = None,
    ngroups: int = 0,
    nelems: int = 0,
    platform: str | None = None,
    source: str = "observed",
) -> None:
    """Record one observed throughput for ``candidate`` under the banded key.

    Observations fold into an EWMA (alpha 0.3) so a noisy rep cannot flip a
    winner by itself; a flip that does happen bumps the store version (and
    with it ``options.trace_fingerprint``, invalidating compiled programs
    that baked the old winner in). Recording is live in record-only mode
    too — that is the mode's entire point. ``source="seed"`` records defer
    to any measured record already holding the key."""
    if not (isinstance(gbps, (int, float)) and gbps > 0):
        return
    key = make_key(
        family, dtype=dtype, ngroups=ngroups, nelems=nelems, platform=platform
    )
    with _LOCK:
        if source == "seed":
            existing = _AUTOTUNE_CACHE.get(key)
            if existing is not None and existing.get("source") != "seed":
                return  # real observations outrank committed evidence
        rec = _AUTOTUNE_CACHE.setdefault(key, {"candidates": {}, "source": source})
        before = _winner(rec)
        slot = rec["candidates"].get(candidate)
        if slot is None:
            rec["candidates"][candidate] = {"gbps": float(gbps), "n": 1}
        else:
            slot["gbps"] = 0.7 * float(slot["gbps"]) + 0.3 * float(gbps)
            slot["n"] = int(slot["n"]) + 1
        rec["source"] = source
        if _winner(rec) != before:
            _AUTOTUNE_STATE["version"] = _AUTOTUNE_STATE.get("version", 0) + 1
        _AUTOTUNE_STATE["records"] = _AUTOTUNE_STATE.get("records", 0) + 1
    if cache_path() is not None:
        _register_atexit()


def _winner(rec: Mapping[str, Any]) -> str | None:
    cands = rec.get("candidates") or {}
    if not cands:
        return None
    return max(cands, key=lambda name: cands[name]["gbps"])


def lookup(
    family: str,
    *,
    dtype: Any = None,
    ngroups: int = 0,
    nelems: int = 0,
    platform: str | None = None,
) -> dict | None:
    """The record for the exact key, else the nearest measured band within
    the family's tolerance (same family/platform/dtype; element band first,
    group band as tiebreak). ``None`` when nothing close enough exists."""
    _ensure_loaded()
    key = make_key(
        family, dtype=dtype, ngroups=ngroups, nelems=nelems, platform=platform
    )
    with _LOCK:
        rec = _AUTOTUNE_CACHE.get(key)
        if rec is not None:
            return rec
        want = _split_key(key)
        if want is None:
            return None
        tolerance = _NEAREST_TOLERANCE.get(family, _NEAREST_TOLERANCE_DEFAULT)
        gtolerance = _NEAREST_TOLERANCE_GROUPS.get(family)
        best_rec, best_dist = None, None
        for other_key, other in _AUTOTUNE_CACHE.items():
            got = _split_key(other_key)
            if got is None or got[:3] != want[:3]:
                continue
            dist = (abs(got[4] - want[4]), abs(got[3] - want[3]))
            if dist[0] > tolerance:
                continue
            if gtolerance is not None and dist[1] > gtolerance:
                continue
            if best_dist is None or dist < best_dist:
                best_rec, best_dist = other, dist
        return best_rec


def decide(
    family: str,
    fallback: str,
    candidates: Iterable[str],
    *,
    dtype: Any = None,
    ngroups: int = 0,
    nelems: int = 0,
) -> str:
    """The observed winner for the key when the tuner is on and has one
    among ``candidates``; the heuristic ``fallback`` otherwise.

    Safe at trace time: a pure host-side dict lookup, no jax calls."""
    if not enabled():
        return fallback
    rec = lookup(family, dtype=dtype, ngroups=ngroups, nelems=nelems)
    if rec is None:
        # no measured band close enough: the analytical cost model (when
        # its plane is on) supplies a cold-start prior for the families it
        # can reason about — measured observations outrank it the moment
        # one lands in the store
        prior = _analytic_prior(
            family, fallback, tuple(candidates),
            dtype=dtype, ngroups=ngroups, nelems=nelems,
        )
        return prior if prior is not None else fallback
    cands = rec.get("candidates") or {}
    eligible = {name: cands[name]["gbps"] for name in cands if name in set(candidates)}
    if not eligible:
        return fallback
    winner = max(eligible, key=lambda name: eligible[name])
    with _LOCK:
        _AUTOTUNE_STATE["hits"] = _AUTOTUNE_STATE.get("hits", 0) + 1
    if winner != fallback:
        logger.debug(
            "autotune: %s -> %r (heuristic said %r)", family, winner, fallback
        )
    return winner


def _analytic_prior(
    family: str,
    fallback: str,
    candidates: tuple,
    *,
    dtype: Any,
    ngroups: int,
    nelems: int,
) -> str | None:
    """``costmodel.analytic_prior`` behind a guard: the tuner must work
    identically when the cost-model plane is off or unimportable."""
    try:
        from .costmodel import analytic_prior

        return analytic_prior(
            family, fallback, candidates,
            dtype=dtype, ngroups=ngroups, nelems=nelems,
        )
    except Exception:  # noqa: BLE001 — a prior failure is a fallback, never a fault
        return None


def decision_fingerprint() -> tuple:
    """The autotune component of ``options.trace_fingerprint``.

    Constant while the tuner is off (record-only mode must not invalidate
    compiled programs); versioned while on, so a record that flips a winner
    retraces exactly once."""
    if not enabled():
        return (False,)
    return (True, _AUTOTUNE_STATE.get("version", 0))


def decision_record() -> dict:
    """A compact summary for bench rows / the CLI: store size, counters,
    and the current per-family winners."""
    _ensure_loaded()
    with _LOCK:
        winners = {}
        for key, rec in sorted(_AUTOTUNE_CACHE.items()):
            name = _winner(rec)
            if name is not None:
                winners[key] = {
                    "winner": name,
                    "gbps": round(rec["candidates"][name]["gbps"], 3),
                    "source": rec.get("source", "observed"),
                }
        return {
            "enabled": enabled(),
            "cache_path": cache_path(),
            "entries": len(_AUTOTUNE_CACHE),
            "sweeps": _AUTOTUNE_STATE.get("sweeps", 0),
            "cache_hits": _AUTOTUNE_STATE.get("hits", 0),
            "version": _AUTOTUNE_STATE.get("version", 0),
            "winners": winners,
        }


# ---------------------------------------------------------------------------
# seeding from committed hardware evidence
# ---------------------------------------------------------------------------


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def seed(root: str | None = None) -> int:
    """Seed the store from the repo's committed measurements: the last
    on-chip sweep (``BENCH_TPU_LAST.json``) and the newest ``BENCH_HISTORY``
    round. Records land under the bench workload's bands with
    ``source="seed"`` so the nearest-band lookup can serve them until real
    observations replace them. Returns how many records were seeded."""
    root = _repo_root() if root is None else root
    seeded = 0
    for path in (
        os.path.join(root, "BENCH_TPU_LAST.json"),
        os.path.join(root, "BENCH_HISTORY", "bench_runs.jsonl"),
    ):
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        lines = text.strip().splitlines()
        if not lines:
            continue
        try:
            payload = json.loads(lines[-1] if path.endswith(".jsonl") else text)
        except ValueError:
            continue
        if isinstance(payload, dict):
            seeded += _seed_from_bench_record(payload)
    logger.debug("autotune: seeded %d record(s) from bench history", seeded)
    return seeded


def _seed_from_bench_record(payload: Mapping[str, Any]) -> int:
    plat = payload.get("platform")
    if not isinstance(plat, str):
        return 0
    workload = payload.get("workload") or {}
    ntime = int(workload.get("ntime", 26304))
    nspace = int(workload.get("nlat", 181)) * int(workload.get("nlon", 360))
    ngroups = int(workload.get("ngroups", 12))
    nelems = ntime * nspace
    count = 0
    sweep = payload.get("impl_sweep_gbps")
    if isinstance(sweep, Mapping):
        for impl, gbps in sweep.items():
            if isinstance(gbps, (int, float)) and gbps > 0:
                record(
                    "segment_sum", str(impl), float(gbps), dtype="float32",
                    ngroups=ngroups, nelems=nelems, platform=plat, source="seed",
                )
                count += 1
    quantile = payload.get("quantile_gbps")
    if isinstance(quantile, Mapping):
        for impl, gbps in quantile.items():
            if isinstance(gbps, (int, float)) and gbps > 0:
                record(
                    "quantile", str(impl), float(gbps), dtype="float32",
                    ngroups=ngroups, nelems=nelems, platform=plat, source="seed",
                )
                count += 1
    highcard = payload.get("highcard")
    if isinstance(highcard, Mapping):
        # the highcard sweep records its own workload bands (universe size
        # and elements actually timed) so the seed lands where it measured
        hc_ngroups = highcard.get("ngroups")
        hc_nelems = highcard.get("nelems")
        if isinstance(hc_ngroups, int) and isinstance(hc_nelems, int):
            for cand in ("dense", "sort"):
                gbps = highcard.get(f"{cand}_gbps")
                if isinstance(gbps, (int, float)) and gbps > 0:
                    record(
                        "highcard", cand, float(gbps), dtype="float32",
                        ngroups=hc_ngroups, nelems=hc_nelems, platform=plat,
                        source="seed",
                    )
                    count += 1
    fused = payload.get("fused")
    if isinstance(fused, Mapping):
        sweep_f = fused.get("fused_sweep_gbps")
        # the fused sweep may have measured a bounded row subset: its
        # record carries the band it actually timed
        fused_nelems = fused.get("nelems")
        if not isinstance(fused_nelems, int) or fused_nelems <= 0:
            fused_nelems = nelems
        if isinstance(sweep_f, Mapping):
            for cand, gbps in sweep_f.items():
                if isinstance(gbps, (int, float)) and gbps > 0:
                    record(
                        "fused", str(cand), float(gbps), dtype="float32",
                        ngroups=ngroups, nelems=fused_nelems, platform=plat,
                        source="seed",
                    )
                    count += 1
    return count


# ---------------------------------------------------------------------------
# in-call micro-sweeps ("first call measures candidates")
# ---------------------------------------------------------------------------


def _sweep_allowed() -> bool:
    with _LOCK:
        return (
            enabled()
            and not _AUTOTUNE_STATE.get("in_sweep")
            and _AUTOTUNE_STATE.get("sweeps", 0) < _SWEEP_BUDGET
        )


def _needs_sweep(family: str, dtype: Any, ngroups: int, nelems: int) -> bool:
    _ensure_loaded()  # a persisted measurement must pre-empt the re-sweep
    key = make_key(family, dtype=dtype, ngroups=ngroups, nelems=nelems)
    with _LOCK:
        if key in _AUTOTUNE_CACHE:
            return False
        # a nearby measured band within tolerance serves decisions just as
        # well — a fresh process must not re-sweep what lookup() would serve
        if lookup(family, dtype=dtype, ngroups=ngroups, nelems=nelems) is not None:
            return False
        # a failed sweep must not retry every call: the attempt is memoized
        attempted = _AUTOTUNE_STATE.setdefault("attempted", set())
        if key in attempted:
            return False
        attempted.add(key)
        return True


def _time_call(fn: Callable[[], Any], reps: int = 2) -> float:
    """Best-of-``reps`` wall seconds of ``fn()`` after one warm call (the
    warm call absorbs trace+compile)."""
    import time

    fn()
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        dt = time.perf_counter() - t0
        best = min(best, dt)
    return best


def _sweep(
    family: str,
    candidates: Iterable[str],
    runner: Callable[[str], Callable[[], Any] | None],
    nbytes: int,
    *,
    dtype: Any,
    ngroups: int,
    nelems: int,
) -> None:
    """Time each candidate's runner on a banded synthetic workload and
    record GB/s. One failing candidate drops out; a sweep that measures
    nothing still counts against the budget (the key is marked attempted
    by the caller)."""
    with _LOCK:
        _AUTOTUNE_STATE["in_sweep"] = True
        _AUTOTUNE_STATE["sweeps"] = _AUTOTUNE_STATE.get("sweeps", 0) + 1
    from . import telemetry

    telemetry.count("autotune.sweeps")
    def measure_one(cand: str) -> None:
        # a failing candidate drops out of the sweep without killing
        # dispatch; this is a one-shot measurement, not a retry loop, so
        # nothing here retries on the swallowed error
        try:
            fn = runner(cand)
            if fn is None:
                return
            seconds = _time_call(fn)
            if seconds > 0:
                record(
                    family, cand, nbytes / seconds / 1e9, dtype=dtype,
                    ngroups=ngroups, nelems=nelems, source="sweep",
                )
        except Exception as exc:  # noqa: BLE001 — a sweep must never kill dispatch
            logger.debug("autotune sweep %s[%s] failed: %s", family, cand, exc)

    try:
        for cand in candidates:
            measure_one(cand)
    finally:
        with _LOCK:
            _AUTOTUNE_STATE["in_sweep"] = False


def _sweep_segment_sum(dtype: Any, ngroups: int, nelems: int) -> None:
    import jax
    import numpy as np

    from .kernels import (
        _on_tpu,
        _pallas_runtime_ok,
        _segment_sum_impl,
        _use_matmul_path,
        generic_kernel,
    )
    from .options import set_options

    n = max(_SWEEP_ROWS, min(_SWEEP_N_MAX, nelems or _SWEEP_N_MAX))
    size = max(1, min(int(ngroups) or 1, n))
    rng = np.random.default_rng(0)
    data = rng.normal(size=(_SWEEP_ROWS, n)).astype(str(dtype), copy=False)
    codes = (np.arange(n, dtype=np.int32) % size).astype(np.int32)
    proxy = jax.ShapeDtypeStruct((n, _SWEEP_ROWS), data.dtype)

    candidates = ["scatter"]
    if _use_matmul_path("sum", proxy, size):
        candidates.append("matmul")
    if _on_tpu() and _pallas_runtime_ok():
        # interpret-mode pallas off-TPU is a debugging aid, never a winner
        with set_options(segment_sum_impl="pallas"):
            if _segment_sum_impl(proxy, size) == "pallas":
                candidates.append("pallas")

    def runner(impl: str) -> Callable[[], Any] | None:
        with set_options(segment_sum_impl=impl):
            if _segment_sum_impl(proxy, size) != impl:
                return None  # guards reroute: timing would mislabel scatter

        # ONE jitted callable per candidate: the impl choice happens at
        # trace time (inside the options context of the first call), and
        # the timed reps then reuse the compiled program — re-jitting per
        # call would time XLA compiles, not the lowering being compared
        jfn = jax.jit(lambda c, v: generic_kernel("nansum", c, v, size=size))

        def run() -> Any:
            with set_options(segment_sum_impl=impl):
                out = jfn(codes, data)
            return np.asarray(out)

        return run

    _sweep(
        "segment_sum", candidates, runner, data.nbytes,
        dtype=dtype, ngroups=ngroups, nelems=nelems,
    )


def _sweep_quantile(dtype: Any, ngroups: int, nelems: int) -> None:
    import jax
    import numpy as np

    from .kernels import generic_kernel
    from .options import set_options

    n = max(_SWEEP_ROWS, min(_SWEEP_N_MAX, nelems or _SWEEP_N_MAX))
    size = max(1, min(int(ngroups) or 1, n))
    rng = np.random.default_rng(0)
    data = rng.normal(size=(_SWEEP_ROWS, n)).astype(str(dtype), copy=False)
    codes = (np.arange(n, dtype=np.int32) % size).astype(np.int32)

    def runner(impl: str) -> Callable[[], Any]:
        # one jitted callable per candidate (see the segment-sum sweep)
        jfn = jax.jit(
            lambda c, v: generic_kernel("nanquantile", c, v, size=size, q=0.5)
        )

        def run() -> Any:
            with set_options(quantile_impl=impl):
                out = jfn(codes, data)
            return np.asarray(out)

        return run

    _sweep(
        "quantile", ("sort", "select"), runner, data.nbytes,
        dtype=dtype, ngroups=ngroups, nelems=nelems,
    )


def _sweep_engine(dtype: Any, nelems: int) -> None:
    import numpy as np

    from .aggregations import generic_aggregate

    n = max(16, min(_SWEEP_ENGINE_N_MAX, nelems or _SWEEP_ENGINE_N_MAX))
    rng = np.random.default_rng(0)
    data = rng.normal(size=n).astype(str(dtype), copy=False)
    size = 16
    codes = (np.arange(n, dtype=np.int64) % size)

    def runner(engine: str) -> Callable[[], Any]:
        def run() -> Any:
            out = generic_aggregate(
                codes, data, engine=engine, func="nansum", size=size, fill_value=0
            )
            return np.asarray(out)

        return run

    # record under the size actually timed (n, not the caller's nelems):
    # the workload is capped, and filing a small-array winner under a
    # large-array band would route big hosts arrays to the numpy engine
    # against the measured crossover
    _sweep(
        "engine", ("numpy", "jax"), runner, data.nbytes,
        dtype=dtype, ngroups=0, nelems=n,
    )


#: highcard-sweep workload caps: the dense-vs-sort crossover is governed by
#: the label-universe size and the present density, so the sweep keeps the
#: caller's density at a capped universe — a 1M-group dense accumulator is
#: only ~8 MB host-side, cheap enough to time honestly
_SWEEP_HIGHCARD_SIZE_MAX = 1 << 20
_SWEEP_HIGHCARD_N_MAX = 1 << 16


def _sweep_highcard(dtype: Any, ngroups: int, n_present: int, nelems: int) -> None:
    """Time the dense jax engine against the sort (present-groups) engine
    on a synthetic workload with the caller's universe size and present
    density (both capped), feeding the "highcard" family the eager
    dense-vs-sort routing consults."""
    import numpy as np

    from .aggregations import generic_aggregate

    n = max(16, min(_SWEEP_HIGHCARD_N_MAX, nelems or _SWEEP_HIGHCARD_N_MAX))
    size = max(2, min(int(ngroups) or 2, _SWEEP_HIGHCARD_SIZE_MAX))
    frac = min(1.0, max(1, int(n_present)) / max(1, int(ngroups)))
    p = max(1, min(int(frac * size), size, n))
    rng = np.random.default_rng(0)
    data = rng.normal(size=n).astype(str(dtype), copy=False)
    present_ids = rng.choice(size, p, replace=False).astype(np.int64)
    codes = present_ids[rng.integers(0, p, n)]

    def runner(engine: str) -> Callable[[], Any]:
        eng = "jax" if engine == "dense" else "sort"

        def run() -> Any:
            out = generic_aggregate(
                codes, data, engine=eng, func="nansum", size=size, fill_value=0
            )
            return np.asarray(out)

        return run

    # record under the universe/elements actually timed (size/n, not the
    # caller's bands): the workload is capped, and a winner measured at the
    # cap must not masquerade as a measurement of a 100x larger universe
    _sweep(
        "highcard", ("dense", "sort"), runner, data.nbytes,
        dtype=dtype, ngroups=size, nelems=n,
    )


def prime_highcard(dtype: Any, ngroups: int, n_present: int, nelems: int) -> None:
    """Highcard-family analogue of :func:`prime_engine`: one budgeted
    dense-vs-sort sweep per banded key, before the routing decision that
    wants to consult it. A no-op unless the tuner is on."""
    if not _sweep_allowed():
        return
    dt = str(dtype)
    if dt not in ("float32", "float64"):
        return
    swept_size = max(2, min(int(ngroups) or 2, _SWEEP_HIGHCARD_SIZE_MAX))
    swept_n = max(16, min(_SWEEP_HIGHCARD_N_MAX, nelems or _SWEEP_HIGHCARD_N_MAX))
    tolerance = _NEAREST_TOLERANCE.get("highcard", _NEAREST_TOLERANCE_DEFAULT)
    if (
        abs(_gband(ngroups) - _gband(swept_size)) > tolerance
        or abs(_eband(nelems) - _eband(swept_n)) > tolerance
    ):
        # the capped sweep could not serve this band anyway (records land
        # under the swept sizes); don't burn budget on it
        return
    try:
        if _needs_sweep("highcard", dt, swept_size, swept_n):
            _sweep_highcard(dt, ngroups, n_present, nelems)
    except Exception as exc:  # noqa: BLE001 — priming must never kill a reduction
        logger.debug("autotune: prime_highcard(%s) failed: %s", dt, exc)


#: reduction families whose chunk kernels ride the additive segment-sum
#: lowering — the ones a segment_sum sweep informs
_ADDITIVE_FAMILIES = frozenset(
    {"sum", "nansum", "mean", "nanmean", "var", "nanvar", "std", "nanstd",
     "count", "len", "nanlen", "any", "all"}
)
_QUANTILE_FAMILIES = frozenset(
    {"quantile", "nanquantile", "median", "nanmedian", "mode", "nanmode"}
)


def prime_reduce(func_name: str, dtype: Any, ngroups: int, nelems: int) -> None:
    """Pre-dispatch hook (non-traced, host side): run the micro-sweeps a
    coming jax-engine reduction will want to consult, once per banded key
    and within the per-process sweep budget. A no-op unless the tuner is
    on."""
    if not _sweep_allowed():
        return
    dt = str(dtype)
    # sweeps synthesize normal floats: other dtypes would burn budget on
    # degenerate workloads whose winner mislabels the real one
    if dt not in ("float32", "float64", "bfloat16"):
        return
    try:
        if func_name in _ADDITIVE_FAMILIES:
            if _needs_sweep("segment_sum", dt, ngroups, nelems):
                _sweep_segment_sum(dt, ngroups, nelems)
        if func_name in _QUANTILE_FAMILIES and _sweep_allowed():
            if _needs_sweep("quantile", dt, ngroups, nelems):
                _sweep_quantile(dt, ngroups, nelems)
    except Exception as exc:  # noqa: BLE001 — priming must never kill a reduction
        logger.debug("autotune: prime_reduce(%s, %s) failed: %s", func_name, dt, exc)


def prime_engine(dtype: Any, nelems: int) -> None:
    """Engine-choice analogue of :func:`prime_reduce` (host arrays only).

    Calls whose element band sits beyond the engine tolerance from the
    capped sweep workload skip the sweep: the measurement could not serve
    them (records land under the swept size), and for arrays that large
    the jax heuristic is already the measured answer."""
    if not _sweep_allowed():
        return
    dt = str(dtype)
    if dt not in ("float32", "float64"):
        return
    swept = max(16, min(_SWEEP_ENGINE_N_MAX, nelems or _SWEEP_ENGINE_N_MAX))
    tolerance = _NEAREST_TOLERANCE.get("engine", _NEAREST_TOLERANCE_DEFAULT)
    if abs(_eband(nelems or swept) - _eband(swept)) > tolerance:
        return
    try:
        if _needs_sweep("engine", dt, 0, nelems):
            _sweep_engine(dt, nelems)
    except Exception as exc:  # noqa: BLE001 — priming must never kill a reduction
        logger.debug("autotune: prime_engine(%s) failed: %s", dt, exc)


# ---------------------------------------------------------------------------
# streaming observations + decisions
# ---------------------------------------------------------------------------


def _bytes_band_candidate(nbytes: int) -> str:
    """Slab sizes are recorded as power-of-two byte candidates ("2^28")."""
    return f"2^{max(0, int(nbytes).bit_length() - 1)}"


def observe_stream(report: Any, *, nbytes: int, nelems: int = 0) -> None:
    """Fold one finished :class:`~flox_tpu.profiling.StreamReport` into the
    store: throughput per prefetch depth and per slab-bytes band, with the
    overlap fraction attached. Record-only safe — runs in every mode."""
    try:
        wall_s = float(report.wall_ms) / 1e3
        if wall_s <= 0 or nbytes <= 0 or not report.slabs:
            return
        gbps = nbytes / wall_s / 1e9
        record(
            "stream_prefetch", str(int(report.prefetch)), gbps,
            nelems=nelems, source="stream",
        )
        slab0 = report.slabs[0]
        slab_bytes = int(nbytes * (slab0.stop - slab0.start) / max(1, _report_span(report)))
        record(
            "stream_slab", _bytes_band_candidate(slab_bytes), gbps,
            nelems=nelems, source="stream",
        )
        from . import telemetry

        if telemetry.enabled():
            telemetry.METRICS.observe("stream.overlap_fraction", report.overlap_fraction)
    except Exception as exc:  # noqa: BLE001 — observation must never break a stream
        logger.debug("autotune: stream observation failed: %s", exc)


def _report_span(report: Any) -> int:
    return sum(int(s.stop) - int(s.start) for s in report.slabs)


def pick_stream_prefetch(default_depth: int, *, nelems: int = 0) -> int:
    """The observed-best prefetch depth for the band (tuner on, record
    known), else ``default_depth``. Prefetch changes only when staging
    happens — never the staged bytes — so adapting it is always
    bit-identical."""
    choice = decide(
        "stream_prefetch", str(int(default_depth)),
        [str(d) for d in (0, 1, 2, 4, 8, 16, 32, 64)], nelems=nelems,
    )
    try:
        return int(choice)
    except ValueError:
        return int(default_depth)


def pick_stream_batch_bytes(default_bytes: int, *, nelems: int = 0) -> int:
    """The observed-best slab byte budget for the band, else the default."""
    fallback = _bytes_band_candidate(default_bytes)
    choice = decide(
        "stream_slab", fallback,
        [f"2^{p}" for p in range(16, 34)], nelems=nelems,
    )
    try:
        power = int(choice.split("^")[1])
    except (IndexError, ValueError):
        return int(default_bytes)
    return 2**power if choice != fallback else int(default_bytes)


# ---------------------------------------------------------------------------
# regression sentinel
# ---------------------------------------------------------------------------


def _history_rounds(history_path: str) -> list[dict]:
    try:
        with open(history_path) as f:
            lines = [line for line in f.read().splitlines() if line.strip()]
    except OSError:
        return []
    rounds = []
    for line in lines:
        try:
            payload = json.loads(line)
        except ValueError:
            continue
        if isinstance(payload, dict):
            rounds.append(payload)
    return rounds


def _last_history_round(
    history_path: str,
    *,
    platform: str | None = None,
    workload: Mapping[str, Any] | None = None,
    skip_rounds: int = 0,
) -> dict | None:
    """The newest round (optionally platform- and workload-matched,
    optionally skipping the last ``skip_rounds`` entries — the CLI compares
    the final round against the one before it). When ``workload`` is given,
    only rounds recording the same shape qualify: GB/s at a CI-smoke shape
    is overhead-dominated and must never read as "a regression" against a
    full-scale round."""
    rounds = _history_rounds(history_path)
    if skip_rounds:
        rounds = rounds[:-skip_rounds] if len(rounds) > skip_rounds else []
    for payload in reversed(rounds):
        if platform is not None and payload.get("platform") != platform:
            continue
        if workload is not None and payload.get("workload") != dict(workload):
            continue
        return payload
    return None


def _history_families(payload: Mapping[str, Any]) -> dict[str, float]:
    """Flatten one bench round into per-family GB/s."""
    out: dict[str, float] = {}
    value = payload.get("value")
    if isinstance(value, (int, float)) and value > 0:
        out["headline"] = float(value)
    for field, prefix in (("impl_sweep_gbps", "segment_sum"), ("quantile_gbps", "quantile")):
        sweep = payload.get(field)
        if isinstance(sweep, Mapping):
            for impl, gbps in sweep.items():
                if isinstance(gbps, (int, float)) and gbps > 0:
                    out[f"{prefix}[{impl}]"] = float(gbps)
    streaming = payload.get("streaming")
    if isinstance(streaming, Mapping):
        for name in ("gbps_sync", "gbps_prefetch"):
            gbps = streaming.get(name)
            if isinstance(gbps, (int, float)) and gbps > 0:
                out[f"streaming[{name.split('_', 1)[1]}]"] = float(gbps)
    return out


def compare_families(
    current: Mapping[str, float],
    previous: Mapping[str, float],
    *,
    threshold: float = _REGRESSION_THRESHOLD,
) -> tuple[dict[str, dict], list[str]]:
    """The verdict core shared by :func:`regression_sentinel` and
    ``benchmarks.sentinel_row``: per-family current-vs-previous rows plus
    the names that dropped below ``(1 - threshold) x previous``."""
    families: dict[str, dict] = {}
    regressed: list[str] = []
    for name, gbps in sorted(current.items()):
        if not (isinstance(gbps, (int, float)) and gbps > 0):
            continue
        prev = previous.get(name)
        row: dict[str, Any] = {"current": round(float(gbps), 3)}
        if isinstance(prev, (int, float)) and prev > 0:
            row["previous"] = round(float(prev), 3)
            row["ratio"] = round(float(gbps) / prev, 3)
            row["regressed"] = float(gbps) < prev * (1.0 - threshold)
            if row["regressed"]:
                regressed.append(name)
        else:
            row["previous"] = None
            row["regressed"] = False
        families[name] = row
    return families, regressed


def regression_sentinel(
    current: Mapping[str, float],
    *,
    history_path: str | None = None,
    threshold: float = _REGRESSION_THRESHOLD,
    platform: str | None = None,
    workload: Mapping[str, Any] | None = None,
    skip_rounds: int = 0,
) -> dict:
    """Diff a round's per-family GB/s against the last ``BENCH_HISTORY``
    round (same platform only — a CPU-fallback round must not be "a
    regression" against an on-chip one; same recorded workload when
    ``workload`` is given — a sub-scale smoke must not be "a regression"
    against a full-size round) and the store's best-known values.
    Returns a report-only verdict dict; the caller decides whether any
    ``regressed`` family fails anything (CI runs it report-only).
    ``skip_rounds`` ignores the newest N history entries — the CLI's
    compare-the-final-round-against-its-predecessor mode."""
    plat = _platform() if platform is None else platform
    history_path = (
        os.path.join(_repo_root(), "BENCH_HISTORY", "bench_runs.jsonl")
        if history_path is None
        else history_path
    )
    prev_round = _last_history_round(
        history_path, platform=plat, workload=workload, skip_rounds=skip_rounds
    )
    previous = {} if prev_round is None else _history_families(prev_round)
    families, regressed = compare_families(current, previous, threshold=threshold)
    return {
        "status": "regression" if regressed else "ok",
        "platform": plat,
        "threshold": threshold,
        "compared_against": history_path if previous else None,
        "regressed": regressed,
        "families": families,
    }


# ---------------------------------------------------------------------------
# CLI: python -m flox_tpu.autotune {report, sentinel}
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m flox_tpu.autotune",
        description="Inspect the flox_tpu autotune store / run the regression sentinel.",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    rep = sub.add_parser("report", help="print the store's winners and counters")
    rep.add_argument("--path", default=None, help="store file (default: the configured path)")
    sen = sub.add_parser(
        "sentinel", help="diff per-family GB/s against the last bench round (report-only)"
    )
    sen.add_argument(
        "--current", default=None,
        help="JSON object of {family: gbps}; default: the last BENCH_HISTORY round itself",
    )
    sen.add_argument("--history", default=None, help="bench_runs.jsonl path")
    sen.add_argument("--platform", default=None, help="platform tag to compare within")
    args = parser.parse_args(argv)

    if args.command == "report":
        if args.path:
            load(args.path)
        print(json.dumps(decision_record(), indent=1))
        return 0

    history = args.history or os.path.join(
        _repo_root(), "BENCH_HISTORY", "bench_runs.jsonl"
    )
    skip_rounds = 0
    plat = args.platform
    workload = None
    if args.current:
        try:
            current = json.loads(args.current)
        except ValueError as exc:
            parser.error(f"--current is not valid JSON: {exc}")
    else:
        latest = _last_history_round(history)
        if latest is None:
            parser.error(f"no readable rounds in {history}")
        # the final round IS the current measurement: compare it against
        # the round before it, within its own platform and (when the round
        # recorded one) its own workload shape
        current = _history_families(latest)
        plat = plat or latest.get("platform")
        workload = latest.get("workload")
        skip_rounds = 1
    verdict = regression_sentinel(
        current, history_path=history, platform=plat, workload=workload,
        skip_rounds=skip_rounds,
    )
    print(json.dumps(verdict, indent=1))
    # report-only: regressions are a verdict in the JSON, never an exit code
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
