"""Global options (parity: /root/reference/flox/options.py:9-65).

The reference exposes two dask-rechunk thresholds; the TPU build keeps those
semantics for its resharding analogue and adds device-policy knobs.
"""

from __future__ import annotations

import contextvars
import os
import re
from typing import Any


def _env_int(name: str, default: int, lo: int = 0, hi: int | None = None) -> int:
    """Env-seeded integer default (CI matrices flip streaming modes this
    way); a malformed or out-of-bounds value falls back rather than
    breaking import — the bounds mirror the ``set_options`` validators, so
    the env cannot seed a value the programmatic API would reject."""
    try:
        value = int(os.environ.get(name, default))
    except ValueError:
        return default
    if value < lo or (hi is not None and value > hi):
        return default
    return value


def _env_float(
    name: str,
    default: float,
    lo: float = 0.0,
    hi: float | None = None,
    lo_open: bool = False,
) -> float:
    """Float analogue of :func:`_env_int` (retry backoff / deadline knobs);
    same fall-back-not-crash contract for malformed env values. Non-finite
    values fall back too: ``nan`` would reach ``time.sleep`` mid-retry and
    ``inf`` would sleep forever — the validators reject both, and the env
    must not be able to seed what ``set_options`` refuses. ``hi`` and
    ``lo_open`` mirror validator bounds of the ``0 < x <= 1`` shape."""
    import math

    try:
        value = float(os.environ.get(name, default))
    except ValueError:
        return default
    if not math.isfinite(value):
        return default
    if value < lo or (lo_open and value == lo) or (hi is not None and value > hi):
        return default
    return value


def _env_choice(name: str, default: str, valid: tuple[str, ...]) -> str:
    """String-enum analogue of :func:`_env_int`: an env value outside the
    valid set falls back to the default rather than breaking import — the
    same cannot-seed-what-set_options-refuses contract."""
    value = os.environ.get(name, default)
    return value if value in valid else default


#: characters a replica id may carry: it becomes a Prometheus label value,
#: a request-id prefix, and a trace-join track name, so label/quote/newline
#: syntax must be unrepresentable rather than escaped at N call sites
_REPLICA_ID_OK = re.compile(r"^[A-Za-z0-9_.:\-]{1,64}$")


def _env_replica(name: str) -> str | None:
    """Env-seeded replica id: a value the :data:`_VALIDATORS` entry would
    reject (label-unsafe characters, overlong) falls back to ``None`` —
    the cannot-seed-what-``set_options``-refuses contract again."""
    value = os.environ.get(name) or None
    if value is not None and _REPLICA_ID_OK.match(value) is None:
        return None
    return value


#: the active option overlay: ``(values, pinned_names)`` installed by
#: :class:`scoped`, or ``None`` outside any scope. A contextvar so each
#: asyncio task / ``contextvars.Context`` sees its own overlay — the
#: serving dispatcher runs concurrent requests with different knobs
#: without racing on the process-global dict below (ROADMAP item 2's
#: serving-critical slice; the span tracer set this precedent in PR 4).
_SCOPE: contextvars.ContextVar[tuple[dict, frozenset] | None] = contextvars.ContextVar(
    "flox_tpu_option_scope", default=None
)


class _ScopedOptions(dict):
    """The process OPTIONS dict with contextvar overlay reads.

    ``OPTIONS[k]`` consults the innermost active :class:`scoped` overlay
    first and falls back to the global value, so every existing read site
    (``OPTIONS["telemetry"]``, ``trace_fingerprint()``, ...) becomes
    scope-aware without changing. Writes (``set_options``, ``update``)
    still hit the global base — a scope is an overlay, never a fork."""

    __slots__ = ()

    def __getitem__(self, key: str) -> Any:
        scope = _SCOPE.get()
        if scope is not None and key in scope[0]:
            return scope[0][key]
        return dict.__getitem__(self, key)

    def get(self, key: str, default: Any = None) -> Any:
        scope = _SCOPE.get()
        if scope is not None and key in scope[0]:
            return scope[0][key]
        return dict.get(self, key, default)


OPTIONS: dict[str, Any] = {
    # Resharding-for-blockwise is applied automatically only when the change
    # it would make is small (same spirit as options.py:9-18).
    "rechunk_blockwise_num_chunks_threshold": _env_float(
        "FLOX_TPU_RECHUNK_BLOCKWISE_NUM_CHUNKS_THRESHOLD", 0.25, 0.0, 1.0, lo_open=True
    ),
    "rechunk_blockwise_chunk_size_threshold": _env_float(
        "FLOX_TPU_RECHUNK_BLOCKWISE_CHUNK_SIZE_THRESHOLD", 1.5, 1.0
    ),
    # TPU policy knobs (no reference analogue):
    # default engine for device arrays. "sort" is the present-groups engine
    # (docs/engines.md "High-cardinality"): accumulators sized by the groups
    # actually present, not the label universe — the remedy the dense-OOM
    # errors name.
    "default_engine": _env_choice(
        "FLOX_TPU_DEFAULT_ENGINE", "jax", ("jax", "numpy", "sort")
    ),
    # label-universe size at which the eager/streaming dispatch starts
    # weighing the sort (present-groups) engine against the dense kernels:
    # below it the dense accumulators are cheap enough that the unique pass
    # is pure overhead; above it the dense-vs-sort choice goes through the
    # "highcard" autotune family (measured bands, then the cost-model
    # analytic prior, then the density heuristic).
    "sort_engine_min_groups": _env_int(
        "FLOX_TPU_SORT_ENGINE_MIN_GROUPS", 1 << 16, 1
    ),
    # additive segment reductions with at most this many groups may use the
    # one-hot matmul (MXU) or Pallas path instead of scatter-add
    "matmul_num_groups_max": _env_int("FLOX_TPU_MATMUL_NUM_GROUPS_MAX", 384, 0),
    # segment-sum implementation: "auto" on TPU tries pallas (after a
    # one-time runtime validation), then the one-hot GEMM (matmul) when its
    # footprint guards pass, then scatter; off-TPU auto is always scatter.
    # Explicit "scatter" | "matmul" | "pallas" override.
    "segment_sum_impl": _env_choice(
        "FLOX_TPU_SEGMENT_SUM_IMPL", "auto",
        ("auto", "scatter", "matmul", "pallas", "radixbin"),
    ),
    # group-count ceiling for the Pallas path (VMEM-bounded; independent of
    # the matmul knob so disabling one path does not disable the other)
    "pallas_num_groups_max": _env_int("FLOX_TPU_PALLAS_NUM_GROUPS_MAX", 512, 0, 512),
    # group-count ceiling for the radix-binning Pallas grid (the
    # high-cardinality sibling of the dense kernel: the group axis is
    # partitioned into VMEM-sized blocks, so the bound is HBM output bytes
    # and grid overhead, not VMEM — sized for the sort engine's compact
    # domains)
    "radixbin_num_groups_max": _env_int(
        "FLOX_TPU_RADIXBIN_NUM_GROUPS_MAX", 1 << 14, 0
    ),
    # Cross-tile accumulation discipline for the Pallas segment-sum, on
    # hardware without float64:
    #   "plain" — a bare f32 running sum (fastest, drifts over many tiles)
    #   "kahan" — compensated summation across tiles (default; recovers
    #             most of the bits a plain running sum loses)
    #   "dd"    — double-double (2×f32 hi/lo carry) with Dekker-split
    #             contractions, for strict-parity users chasing the
    #             float64 oracle (BASELINE "bit-exact float64 means")
    "pallas_accum": _env_choice("FLOX_TPU_PALLAS_ACCUM", "kahan", ("plain", "kahan", "dd")),
    # per-block budget for the GEMM path's (N, 4*kb) marker stacking; wide-K
    # inputs loop column blocks of this many bytes instead of materializing
    # the whole stacking (256 MB default: big enough to keep the MXU fed,
    # small next to HBM)
    "matmul_block_bytes": _env_int("FLOX_TPU_MATMUL_BLOCK_BYTES", 2**28, 2**20),
    # segment-min/max implementation: "auto" on TPU uses the Pallas VPU
    # select-reduce kernel (after runtime validation) instead of scatter,
    # which serializes; off-TPU auto is scatter. Explicit override as above.
    "segment_minmax_impl": _env_choice(
        "FLOX_TPU_SEGMENT_MINMAX_IMPL", "auto", ("auto", "scatter", "pallas")
    ),
    # the min/max kernel's VPU work grows linearly with the group count
    # (one select+reduce pass per group per tile); past this many groups the
    # kernel is no longer clearly ahead of scatter
    "pallas_minmax_num_groups_max": _env_int(
        "FLOX_TPU_PALLAS_MINMAX_NUM_GROUPS_MAX", 128, 0, 512
    ),
    # grouped cumulative scans: "auto" on TPU uses the Pallas triangular-
    # matmul kernel (one HBM pass) instead of the sort + log-depth
    # segmented scan; off-TPU auto stays on the segmented path.
    "scan_impl": _env_choice("FLOX_TPU_SCAN_IMPL", "auto", ("auto", "segmented", "pallas")),
    # the scan kernel's carry gather/update matmuls scale with the group
    # count; past ~the lane-tile width they dominate the triangular matmul
    "pallas_scan_num_groups_max": _env_int("FLOX_TPU_PALLAS_SCAN_NUM_GROUPS_MAX", 128, 0, 512),
    # grouped order statistics: "sort" = two-key lexicographic lax.sort;
    # "select" = sort-free MSB radix bisection — nbits counting passes,
    # each a segment-sum riding the MXU one-hot GEMM / Pallas path. "auto"
    # currently resolves to sort; the bench sweep measures both on chip
    # (VERDICT r3 #3) and auto flips when hardware numbers justify it.
    "quantile_impl": _env_choice("FLOX_TPU_QUANTILE_IMPL", "auto", ("auto", "sort", "select")),
    # HBM ceiling for dense (..., size) device intermediates (VERDICT r3 #6:
    # a ~10^6-label run used to OOM with no guard). Estimated footprint
    # above this either auto-routes map-reduce/cohorts to the blocked
    # psum-per-owner-block program (additive combines: intermediates are
    # (..., size/ndev) from the start) or raises with the alternatives.
    # Default 8 GiB: half a v5e chip's HBM, leaving room for the data.
    "dense_intermediate_bytes_max": _env_int(
        "FLOX_TPU_DENSE_INTERMEDIATE_BYTES_MAX", 8 * 2**30, 2**20
    ),
    # Streaming pipeline (flox_tpu/pipeline.py): how many slabs the
    # background staging pool may hold in flight — slab i+k loads, pads and
    # device_puts while the device reduces slab i. 0 = synchronous inline
    # staging (the pre-pipeline loop; staged bytes are identical either
    # way). Depth > 1 also overlaps the loads themselves, so the loader
    # must tolerate concurrent (start, stop) calls; a stateful serial
    # reader should run with 1. Env-seeded (FLOX_TPU_STREAM_PREFETCH) so
    # CI can sweep both modes without code changes.
    "stream_prefetch": _env_int("FLOX_TPU_STREAM_PREFETCH", 2, 0, 64),
    # sync the streaming carry every K dispatched steps so in-flight slabs
    # (and their staged device copies) cannot pile up unboundedly in HBM
    # when the host runs ahead of the device; 0 disables the throttle
    "stream_dispatch_depth": _env_int("FLOX_TPU_STREAM_DISPATCH_DEPTH", 8, 0),
    # donate the carry state into the jitted streaming steps so accumulator
    # HBM is reused across slabs: "auto" probes the backend once (platforms
    # that cannot alias donated buffers fall back to undonated steps),
    # "on"/"off" force it
    "stream_donate": _env_choice("FLOX_TPU_STREAM_DONATE", "auto", ("auto", "on", "off")),
    # Streaming resilience (flox_tpu/resilience.py): how many times a slab's
    # load+stage is retried after a TRANSIENT failure (IO/RPC hiccups per
    # resilience.classify_error; programming errors never retry) before the
    # original exception surfaces. retries + 1 total attempts per slab.
    "stream_retries": _env_int("FLOX_TPU_STREAM_RETRIES", 2, 0, 1000),
    # base backoff sleep in seconds between retry attempts, doubled per
    # attempt (backoff * 2**attempt)
    "stream_backoff": _env_float("FLOX_TPU_STREAM_BACKOFF", 0.05),
    # per-slab deadline in seconds across all staging attempts + backoffs of
    # one slab; a retry that would sleep past it raises TimeoutError instead.
    # 0 disables the deadline.
    "stream_slab_timeout": _env_float("FLOX_TPU_STREAM_SLAB_TIMEOUT", 0.0),
    # device_get the streaming carry to a host-side snapshot every K
    # processed slabs, so a killed run resumes bit-identically from the last
    # snapshot instead of restarting an hours-long stream. 0 disables
    # checkpointing (and its per-stream key fingerprinting) entirely.
    "stream_checkpoint_every": _env_int("FLOX_TPU_STREAM_CHECKPOINT_EVERY", 0, 0),
    # optional spill target for snapshots: a directory (one .npz per stream
    # identity) or a literal .npz path — the cross-process resume path. None
    # keeps snapshots in the in-process registry only.
    "stream_checkpoint_path": os.environ.get("FLOX_TPU_STREAM_CHECKPOINT_PATH") or None,
    # Durable incremental aggregation stores (flox_tpu/store.py). store_root:
    # the directory the serve-layer store ops create/open stores under (one
    # subdirectory per store name); None disables the serve store surface.
    "store_root": os.environ.get("FLOX_TPU_STORE_ROOT") or None,
    # auto-compact when a store holds more than this many live delta
    # segments after an append; 0 keeps compaction manual (the compact op)
    "store_compact_threshold": _env_int("FLOX_TPU_STORE_COMPACT_THRESHOLD", 0, 0),
    # "off" skips the per-write fsyncs (file + directory) on journal and
    # segment landings — for tests and throwaway stores only: without them
    # a power loss can reorder the WAL protocol's commit points
    "store_fsync": _env_choice("FLOX_TPU_STORE_FSYNC", "on", ("on", "off")),
    # Telemetry (flox_tpu/telemetry.py): master switch for the hierarchical
    # span tracer, the metrics registry, and the jax compile/retrace
    # listener. Off (the default) is a true no-op — no span objects are
    # allocated and counters stay untouched. Env-seeded so CI can run the
    # whole suite instrumented without code changes.
    "telemetry": bool(_env_int("FLOX_TPU_TELEMETRY", 0, 0, 1)),
    # "basic" records phase-level spans (factorize/dispatch/combine/
    # finalize, stream passes); "detailed" adds per-slab staging spans and
    # per-kernel dispatch counters on the hot paths
    "telemetry_level": _env_choice(
        "FLOX_TPU_TELEMETRY_LEVEL", "basic", ("basic", "detailed")
    ),
    # stream finished telemetry records to this file: *.jsonl appends
    # incrementally as spans finish, any other path is written as one
    # Chrome trace-event JSON (ui.perfetto.dev-loadable) at flush/exit.
    # None keeps records in the in-process buffer (telemetry.spans()).
    "telemetry_export_path": os.environ.get("FLOX_TPU_TELEMETRY_EXPORT_PATH") or None,
    # Autotuner (flox_tpu/autotune.py): when on, every `auto` dispatch
    # decision (engine, segment_sum_impl, quantile sort-vs-select, streaming
    # slab/prefetch sizing) consults the per-host measurement store and
    # picks the observed winner; first call measures candidates (budgeted
    # micro-sweeps) or serves seeds from BENCH_HISTORY. Off (the default)
    # is record-only: observations still accrete, dispatch stays on the
    # static heuristics — bit-identical to the pre-autotune tree.
    "autotune": bool(_env_int("FLOX_TPU_AUTOTUNE", 0, 0, 1)),
    # persistence target for the autotune store: an atomic-JSON file path
    # loaded lazily at first consult and saved at exit / autotune.save().
    # None keeps the store in-process only.
    "autotune_cache_path": os.environ.get("FLOX_TPU_AUTOTUNE_CACHE_PATH") or None,
    # Below this many elements a host array reduces faster on the numpy
    # engine than through jit dispatch (engine=None heuristic; measured
    # round 5 — see docs/engines.md). An OPTIONS entry so accelerator
    # deployments can tune the crossover without a code change (ADVICE r5);
    # the autotuner's measured "engine" records override it when enabled.
    "numpy_engine_max_elems": _env_int("FLOX_TPU_NUMPY_ENGINE_MAX_ELEMS", 32768, 0),
    # Serving layer (flox_tpu/serve/): admission-control bound on requests
    # pending in the dispatcher (queued + executing). A submit beyond this
    # depth is load-shed immediately (serve.LoadShedError) instead of
    # growing an unbounded backlog the device can never drain. 0 disables
    # admission control.
    "serve_queue_depth": _env_int("FLOX_TPU_SERVE_QUEUE_DEPTH", 64, 0),
    # default per-request deadline in seconds (queue wait + device time): a
    # request still undispatched past it is cancelled with
    # serve.DeadlineExceededError, never dispatched. 0 = no deadline.
    # Per-request deadline= overrides.
    "serve_deadline": _env_float("FLOX_TPU_SERVE_DEADLINE", 0.0),
    # how many program-compatible small requests the dispatcher may stack
    # into ONE device dispatch (a leading batch axis over identical-shape
    # payloads sharing codes + program). 1 disables micro-batching.
    "serve_microbatch_max": _env_int("FLOX_TPU_SERVE_MICROBATCH_MAX", 8, 1, 1024),
    # seconds a freshly opened coalescing/micro-batch window stays open for
    # compatible concurrent requests to join before the batch dispatches.
    # 0 still yields the event loop once (same-tick submits coalesce);
    # higher values trade first-request latency for batching opportunity.
    "serve_batch_window": _env_float("FLOX_TPU_SERVE_BATCH_WINDOW", 0.002, 0.0, 60.0),
    # elements ceiling for micro-batch eligibility: requests above it
    # dispatch alone (stacking huge payloads would serialize the batch
    # behind one giant program rather than amortize dispatch overhead)
    "serve_microbatch_max_elems": _env_int(
        "FLOX_TPU_SERVE_MICROBATCH_MAX_ELEMS", 1 << 20, 0
    ),
    # Serve fault domain (flox_tpu/serve/): seconds a draining replica
    # (SIGTERM or {"op":"shutdown"}) waits for in-flight requests to finish
    # before exiting — admission stops and /readyz flips 503 the moment the
    # drain begins; requests still unfinished past the budget are failed
    # (never silently dropped). 0 = exit as soon as admission has stopped.
    "serve_drain_timeout": _env_float("FLOX_TPU_SERVE_DRAIN_TIMEOUT", 30.0),
    # seconds a single device dispatch may run before the watchdog fails its
    # waiters (typed WatchdogTimeoutError), flight-dumps, and leaves a
    # capture hint — a wedged dispatch must not hang the whole queue.
    # 0 (the default) disables the watchdog.
    "serve_watchdog_timeout": _env_float("FLOX_TPU_SERVE_WATCHDOG_TIMEOUT", 0.0),
    # consecutive fatal failures on ONE program key that open its circuit
    # breaker: further identical-program requests fast-fail with a typed
    # CircuitOpenError (no device dispatch burned) until the cooldown
    # elapses and a half-open probe request closes it. 0 disables breakers.
    "serve_breaker_threshold": _env_int("FLOX_TPU_SERVE_BREAKER_THRESHOLD", 5, 0, 10_000),
    # seconds an open breaker fast-fails before admitting one half-open
    # probe request (success closes the breaker, failure re-opens it)
    "serve_breaker_cooldown": _env_float("FLOX_TPU_SERVE_BREAKER_COOLDOWN", 30.0),
    # Resident dataset registry (flox_tpu/serve/registry.py): fraction of
    # the device's reported HBM capacity (device.memory_stats()
    # bytes_limit — the PR 13 hbm.bytes_limit gauge source) the registry
    # may pin. Past it, unpinned entries are LRU-evicted at put time.
    "registry_budget_fraction": _env_float(
        "FLOX_TPU_REGISTRY_BUDGET_FRACTION", 0.5, 0.0, 1.0, lo_open=True
    ),
    # absolute device-byte budget used where the backend reports NO memory
    # limit (CPU test rigs): same LRU eviction against this ceiling.
    # 0 disables budget enforcement entirely.
    "registry_budget_bytes": _env_int(
        "FLOX_TPU_REGISTRY_BUDGET_BYTES", 1 << 30, 0
    ),
    # dataset arrays at or above this many bytes are mesh-sharded over the
    # trailing axis at put time (feeding the parallel plane's per-shard
    # codes directly); below it they stay single-chip. 0 = never shard.
    "registry_shard_threshold_bytes": _env_int(
        "FLOX_TPU_REGISTRY_SHARD_THRESHOLD_BYTES", 1 << 30, 0
    ),
    # AOT persistence root (flox_tpu/serve/aot.py): the JAX persistent
    # compilation cache directory + the warmup manifest next to it. A
    # fresh replica pointed at a warm dir serves its first request with
    # zero backend compiles. None disables persistence.
    "serve_aot_dir": os.environ.get("FLOX_TPU_SERVE_AOT_DIR") or None,
    # Observability plane (flox_tpu/exposition.py): TCP port for the
    # stdlib-HTTP /metrics (Prometheus text format) + /healthz + /readyz
    # endpoint. 0 (the default) leaves the endpoint off; python -m
    # flox_tpu.serve starts it automatically when nonzero (or with
    # --metrics-port).
    "metrics_port": _env_int("FLOX_TPU_METRICS_PORT", 0, 0, 65535),
    # Flight recorder (flox_tpu/telemetry.py): dump target for the bounded
    # ring of recent span/event records on fatal faults, unhandled serve
    # loop exceptions, and SIGTERM/SIGUSR2 — a JSON-lines file readable by
    # `python -m flox_tpu.telemetry report`. None disables dumping (the
    # ring still fills while telemetry is on; telemetry.flight_dump(path)
    # can dump it anywhere on demand).
    "flight_recorder_path": os.environ.get("FLOX_TPU_FLIGHT_RECORDER_PATH") or None,
    # how many recent records the flight-recorder ring retains (a bounded
    # deque — fixed allocation, the oldest record falls out first)
    "flight_recorder_size": _env_int("FLOX_TPU_FLIGHT_RECORDER_SIZE", 2048, 16, 1_000_000),
    # On-chip profiling (flox_tpu/profiling.py): default capture root for
    # profiling.trace() and the on-demand capture surface (/debug/profile,
    # the serve "profile" op, SIGUSR1). Captures rotate inside this
    # directory (profile_keep bounds how many are retained). None means no
    # default root — trace() then requires an explicit logdir and the
    # on-demand capture answers "unconfigured".
    "profile_dir": os.environ.get("FLOX_TPU_PROFILE_DIR") or None,
    # how many rotated captures profile_dir retains: starting capture K+1
    # deletes the oldest, so an operator poking /debug/profile in a loop
    # can never fill the disk
    "profile_keep": _env_int("FLOX_TPU_PROFILE_KEEP", 8, 1, 1024),
    # Saturation sampler (flox_tpu/telemetry.py): seconds between samples
    # of the live saturation gauges (serve.queue_depth, serve.inflight
    # batches, stream.prefetch_occupancy, periodic device.memory_stats()).
    # 0 (the default) keeps the daemon thread off — /metrics then shows
    # only the post-hoc histograms; nonzero makes saturation visible
    # BETWEEN requests, which is when an operator is staring at a stall.
    "metrics_sample_interval": _env_float(
        "FLOX_TPU_METRICS_SAMPLE_INTERVAL", 0.0, 0.0, 3600.0
    ),
    # Fleet identity (flox_tpu/telemetry.py + fleet.py): this replica's
    # stable name in a multi-replica deployment. When set, every /metrics
    # series and /debug/costs payload carries replica="<id>" (plus the
    # host), generated request ids are prefixed "<id>:" so they never
    # collide across the fleet, and jsonl/flight exports are stamped with
    # it for tools/trace_join.py. None (the default) keeps the
    # single-replica surfaces byte-identical to PR 8/9.
    "replica_id": _env_replica("FLOX_TPU_REPLICA_ID"),
    # Fleet federation (flox_tpu/fleet.py): seconds between scrape rounds
    # of the `python -m flox_tpu.fleet federate` aggregator (each round
    # pulls every replica's /metrics + /debug/costs + /readyz)
    "fleet_scrape_interval": _env_float(
        "FLOX_TPU_FLEET_SCRAPE_INTERVAL", 2.0, 0.05, 3600.0, lo_open=False
    ),
    # TCP port the federator serves the merged view on (0 = ephemeral,
    # printed at startup); `fleet federate --port` overrides
    "fleet_port": _env_int("FLOX_TPU_FLEET_PORT", 0, 0, 65535),
    # default replica set for the fleet CLIs: comma-separated base URLs
    # ("http://127.0.0.1:8971,http://127.0.0.1:8972" — name=url pairs
    # allowed: "a=http://...") consumed when `fleet federate` / `fleet
    # top` get no --replicas flag. None requires the flag.
    "fleet_replicas": os.environ.get("FLOX_TPU_FLEET_REPLICAS") or None,
    # Analytical cost model (flox_tpu/costmodel.py): when on (with
    # telemetry), every compile site records a compiled-program card
    # (XLA's analytical flops / bytes accessed / memory footprint via
    # Compiled.cost_analysis()/memory_analysis(), a roofline predicted_ms)
    # and dispatches publish program.utilization / program.predicted_ms
    # gauges plus the /debug/programs surface. The analysis pass compiles
    # each unique program ONE extra time purely for inspection (never
    # executed; counted on costmodel.card_* — jax.compiles untouched), so
    # the plane is opt-in. Off (the default) is a true no-op.
    "costmodel": bool(_env_int("FLOX_TPU_COSTMODEL", 0, 0, 1)),
    # drift-sentinel flag ratio: a program whose observed per-dispatch
    # device time exceeds threshold x the model (roofline prediction
    # floored at costmodel_overhead_ms) is flagged by
    # costmodel.drift_report — the "silently got 10x slower after a JAX
    # upgrade" detector
    "costmodel_drift_threshold": _env_float(
        "FLOX_TPU_COSTMODEL_DRIFT_THRESHOLD", 10.0, 1.0, 1e6
    ),
    # dispatch-overhead floor (ms) for the drift model: microsecond-scale
    # analytical predictions are floored here before the ratio, so tiny
    # programs are judged against dispatch overhead (an honest CPU run
    # must exit clean) while genuinely slow programs still flag
    "costmodel_overhead_ms": _env_float(
        "FLOX_TPU_COSTMODEL_OVERHEAD_MS", 25.0, 0.0, 60_000.0
    ),
    # SLO plane (flox_tpu/slo.py): path of the declarative objective spec
    # consumed by slo.load_spec — JSON, or TOML for *.toml. None (the
    # default) uses the built-in objectives (latency / availability /
    # correctness / freshness under Google-SRE fast+slow burn windows).
    # An unreadable or invalid spec raises ValueError at the surface that
    # evaluates it (/slo answers 500), never a silent default fallback.
    "slo_path": os.environ.get("FLOX_TPU_SLO_PATH") or None,
    # seconds between canary-prober cycles in `python -m flox_tpu.serve`:
    # known-answer requests across the op matrix, billed to the reserved
    # "__canary__" tenant, feeding the correctness SLO. 0 (the default)
    # keeps the prober off; the serve CLI's --canary-interval overrides.
    "slo_canary_interval": _env_float("FLOX_TPU_SLO_CANARY_INTERVAL", 0.0, 0.0, 3600.0),
}

# single source of truth for the accumulation disciplines — referenced by
# both the set_options validator and segment_sum_pallas's argument check
VALID_ACCUMS = ("plain", "kahan", "dd")

_VALIDATORS = {
    "rechunk_blockwise_num_chunks_threshold": lambda x: 0 < x <= 1,
    "rechunk_blockwise_chunk_size_threshold": lambda x: x >= 1,
    "default_engine": lambda x: x in ("jax", "numpy", "sort"),
    "sort_engine_min_groups": lambda x: _is_int(x) and x >= 1,
    "matmul_num_groups_max": lambda x: isinstance(x, int) and x >= 0,
    "segment_sum_impl": lambda x: x in ("auto", "scatter", "matmul", "pallas", "radixbin"),
    "pallas_num_groups_max": lambda x: isinstance(x, int) and 0 <= x <= 512,
    "radixbin_num_groups_max": lambda x: isinstance(x, int) and x >= 0,
    "pallas_accum": lambda x: x in VALID_ACCUMS,
    "matmul_block_bytes": lambda x: isinstance(x, int) and x >= 2**20,
    "segment_minmax_impl": lambda x: x in ("auto", "scatter", "pallas"),
    "pallas_minmax_num_groups_max": lambda x: isinstance(x, int) and 0 <= x <= 512,
    "scan_impl": lambda x: x in ("auto", "segmented", "pallas"),
    "pallas_scan_num_groups_max": lambda x: isinstance(x, int) and 0 <= x <= 512,
    "dense_intermediate_bytes_max": lambda x: isinstance(x, int) and x >= 2**20,
    "quantile_impl": lambda x: x in ("auto", "sort", "select"),
    # streaming knobs are validated AT SET TIME: a negative depth or retry
    # count must raise here, not hang or crash slabs into an hours-long
    # stream (bool is excluded — True/False sneaking in as 1/0 is a bug)
    "stream_prefetch": lambda x: _is_int(x) and 0 <= x <= 64,
    "stream_dispatch_depth": lambda x: _is_int(x) and x >= 0,
    "stream_donate": lambda x: x in ("auto", "on", "off"),
    "stream_retries": lambda x: _is_int(x) and 0 <= x <= 1000,
    "stream_backoff": lambda x: _is_finite_num(x) and x >= 0,
    "stream_slab_timeout": lambda x: _is_finite_num(x) and x >= 0,
    "stream_checkpoint_every": lambda x: _is_int(x) and x >= 0,
    "stream_checkpoint_path": lambda x: x is None or (
        isinstance(x, (str, os.PathLike)) and bool(str(x))
    ),
    # store knobs: same at-set-time discipline — a bad root path or a
    # negative compaction threshold raises here, not at the first append
    "store_root": lambda x: x is None or (
        isinstance(x, (str, os.PathLike)) and bool(str(x))
    ),
    "store_compact_threshold": lambda x: _is_int(x) and x >= 0,
    "store_fsync": lambda x: x in ("on", "off"),
    # telemetry knobs are validated AT SET TIME like the stream knobs: a
    # bad level or a non-path export target raises here, not mid-trace
    "telemetry": lambda x: isinstance(x, bool),
    "telemetry_level": lambda x: x in ("basic", "detailed"),
    "telemetry_export_path": lambda x: x is None or (
        isinstance(x, (str, os.PathLike)) and bool(str(x))
    ),
    # autotune knobs: same at-set-time discipline — a non-bool switch or a
    # pathless persistence target raises here, not mid-dispatch
    "autotune": lambda x: isinstance(x, bool),
    "autotune_cache_path": lambda x: x is None or (
        isinstance(x, (str, os.PathLike)) and bool(str(x))
    ),
    "numpy_engine_max_elems": lambda x: _is_int(x) and x >= 0,
    # serving knobs: same at-set-time discipline — a negative depth or a
    # non-finite deadline raises here, not inside the dispatcher loop
    "serve_queue_depth": lambda x: _is_int(x) and x >= 0,
    "serve_deadline": lambda x: _is_finite_num(x) and x >= 0,
    "serve_microbatch_max": lambda x: _is_int(x) and 1 <= x <= 1024,
    "serve_batch_window": lambda x: _is_finite_num(x) and 0 <= x <= 60,
    "serve_microbatch_max_elems": lambda x: _is_int(x) and x >= 0,
    # serve fault-domain knobs: same at-set-time discipline — a negative
    # drain budget or a non-finite cooldown raises here, not mid-drain or
    # inside the breaker check
    "serve_drain_timeout": lambda x: _is_finite_num(x) and x >= 0,
    "serve_watchdog_timeout": lambda x: _is_finite_num(x) and x >= 0,
    "serve_breaker_threshold": lambda x: _is_int(x) and 0 <= x <= 10_000,
    "serve_breaker_cooldown": lambda x: _is_finite_num(x) and x >= 0,
    # registry knobs: same at-set-time discipline — a fraction outside
    # (0, 1] or a negative byte budget raises here, not inside a put's
    # eviction sweep
    "registry_budget_fraction": lambda x: _is_finite_num(x) and 0 < x <= 1,
    "registry_budget_bytes": lambda x: _is_int(x) and x >= 0,
    "registry_shard_threshold_bytes": lambda x: _is_int(x) and x >= 0,
    "serve_aot_dir": lambda x: x is None or (
        isinstance(x, (str, os.PathLike)) and bool(str(x))
    ),
    # observability-plane knobs: same at-set-time discipline — a port out
    # of TCP range or a zero-capacity ring raises here, not at scrape time
    "metrics_port": lambda x: _is_int(x) and 0 <= x <= 65535,
    "flight_recorder_path": lambda x: x is None or (
        isinstance(x, (str, os.PathLike)) and bool(str(x))
    ),
    "flight_recorder_size": lambda x: _is_int(x) and 16 <= x <= 1_000_000,
    # cost/profiling-plane knobs: same at-set-time discipline — an empty
    # capture root or a negative sampling interval raises here, not inside
    # the capture thread or the sampler daemon
    "profile_dir": lambda x: x is None or (
        isinstance(x, (str, os.PathLike)) and bool(str(x))
    ),
    "profile_keep": lambda x: _is_int(x) and 1 <= x <= 1024,
    "metrics_sample_interval": lambda x: _is_finite_num(x) and 0 <= x <= 3600,
    # fleet knobs: same at-set-time discipline — an empty or label-unsafe
    # replica id (it becomes a Prometheus label value and a request-id
    # prefix) or a runaway scrape interval raises here, not at scrape time
    "replica_id": lambda x: x is None or (
        isinstance(x, str) and bool(x) and _REPLICA_ID_OK.match(x) is not None
    ),
    "fleet_scrape_interval": lambda x: _is_finite_num(x) and 0.05 <= x <= 3600,
    "fleet_port": lambda x: _is_int(x) and 0 <= x <= 65535,
    "fleet_replicas": lambda x: x is None or (isinstance(x, str) and bool(x)),
    # cost-model knobs: same at-set-time discipline — a non-bool switch, a
    # sub-1x drift threshold (everything would flag), or a negative
    # overhead floor raises here, not inside the dispatch-time gauge join
    "costmodel": lambda x: isinstance(x, bool),
    "costmodel_drift_threshold": lambda x: _is_finite_num(x) and 1 <= x <= 1e6,
    "costmodel_overhead_ms": lambda x: _is_finite_num(x) and 0 <= x <= 60_000,
    # SLO-plane knobs: a bad spec path or a runaway canary period raises
    # here, not at the first evaluation (spec CONTENT is validated by
    # slo.load_spec at read time — the path can point anywhere writable)
    "slo_path": lambda x: x is None or (
        isinstance(x, (str, os.PathLike)) and bool(str(x))
    ),
    "slo_canary_interval": lambda x: _is_finite_num(x) and 0 <= x <= 3600,
}

# rebind the literal through the overlay-aware view: same object contents,
# scope-aware reads everywhere `from .options import OPTIONS` already lands
OPTIONS = _ScopedOptions(OPTIONS)


def _is_int(x: Any) -> bool:
    return isinstance(x, int) and not isinstance(x, bool)


def _is_num(x: Any) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def _is_finite_num(x: Any) -> bool:
    import math

    return _is_num(x) and math.isfinite(x)


def trace_fingerprint() -> tuple:
    """Options that are read at TRACE time inside jitted programs.

    Any cache of compiled programs must include this in its key, or a
    set_options() change would silently keep serving stale kernels.
    """
    return (
        OPTIONS["segment_sum_impl"],
        OPTIONS["matmul_num_groups_max"],
        OPTIONS["pallas_num_groups_max"],
        OPTIONS["radixbin_num_groups_max"],
        OPTIONS["pallas_accum"],
        OPTIONS["matmul_block_bytes"],
        OPTIONS["segment_minmax_impl"],
        OPTIONS["pallas_minmax_num_groups_max"],
        OPTIONS["scan_impl"],
        OPTIONS["pallas_scan_num_groups_max"],
        OPTIONS["quantile_impl"],
        # build-time rather than trace-time, but the same staleness rule
        # applies: a cached step compiled with donation must not serve a
        # stream_donate="off" session (and vice versa)
        OPTIONS["stream_donate"],
        # the autotuner's decisions are read at trace time wherever the
        # policies above are; a record that flips a winner bumps this, so
        # cached programs never serve a stale lowering choice. Constant
        # while the tuner is off (record-only mode never retraces).
        _autotune_fingerprint(),
    )


def _autotune_fingerprint() -> tuple:
    from .autotune import decision_fingerprint

    return decision_fingerprint()


#: option names the user pinned explicitly — via the env mirror at import
#: or any set_options() call since. The autotuner treats only UNPINNED
#: knobs as an "auto" surface it may adapt (an explicit
#: set_options(stream_prefetch=2) means 2, even with the tuner on).
_EXPLICIT_OPTIONS: set[str] = {
    name
    for name, env in (("stream_prefetch", "FLOX_TPU_STREAM_PREFETCH"),)
    if env in os.environ
}


def explicitly_set(name: str) -> bool:
    """Whether ``name`` was pinned by the user (env mirror, set_options, or
    the innermost :class:`scoped` overlay) rather than riding its built-in
    default. Scope pins end with the scope: provenance respects the active
    overlay exactly as values do."""
    scope = _SCOPE.get()
    if scope is not None and name in scope[1]:
        return True
    return name in _EXPLICIT_OPTIONS


def scope_overrides() -> dict:
    """The active :class:`scoped` overlay, merged innermost-wins — ``{}``
    outside any scope. The serving dispatcher folds this into each
    request's program key and execution overlay, so a submit made under an
    ambient scope never shares a dispatch with differently-scoped peers."""
    scope = _SCOPE.get()
    return dict(scope[0]) if scope is not None else {}


class scoped:
    """Context-scoped option overlay: concurrent callers, isolated knobs.

    >>> import flox_tpu
    >>> from flox_tpu.options import OPTIONS, scoped
    >>> with scoped(default_engine="numpy"):
    ...     OPTIONS["default_engine"]
    'numpy'
    >>> OPTIONS["default_engine"]
    'jax'

    Unlike :class:`set_options` (which mutates the process-global dict and
    therefore races under concurrency), ``scoped`` installs a contextvar
    overlay visible only to the current context — asyncio tasks inherit a
    copy at creation, threads start clean, and nested scopes merge with the
    innermost value winning. The serving dispatcher wraps every request's
    execution in its requested scope, so N concurrent requests with
    different engines/telemetry levels read N different views of the same
    OPTIONS object. Validation matches ``set_options`` (bad values raise at
    entry, never mid-dispatch); ``explicitly_set`` reports overlay names as
    pinned while the scope is live, so the autotuner never adapts a knob a
    request pinned.
    """

    def __init__(self, **overrides: Any) -> None:
        for k, v in overrides.items():
            if k not in OPTIONS:
                raise ValueError(
                    f"argument name {k!r} is not in the set of valid options {set(OPTIONS)!r}"
                )
            if k in _VALIDATORS and not _VALIDATORS[k](v):
                raise ValueError(f"option {k!r} given an invalid value: {v!r}")
        self._overrides = overrides
        self._token: contextvars.Token | None = None

    def __enter__(self) -> "scoped":
        parent = _SCOPE.get()
        if parent is None:
            values, pins = dict(self._overrides), frozenset(self._overrides)
        else:
            values = {**parent[0], **self._overrides}
            pins = parent[1] | frozenset(self._overrides)
        self._token = _SCOPE.set((values, pins))
        return self

    def __exit__(self, *args: Any) -> None:
        if self._token is not None:
            _SCOPE.reset(self._token)
            self._token = None


class set_options:
    """Context manager / global setter for options (options.py:21-65 parity).

    >>> import flox_tpu
    >>> with flox_tpu.set_options(default_engine="numpy"):
    ...     pass
    """

    def __init__(self, **kwargs: Any) -> None:
        self.old: dict[str, Any] = {}
        for k, v in kwargs.items():
            if k not in OPTIONS:
                raise ValueError(f"argument name {k!r} is not in the set of valid options {set(OPTIONS)!r}")
            if k in _VALIDATORS and not _VALIDATORS[k](v):
                raise ValueError(f"option {k!r} given an invalid value: {v!r}")
            # snapshot the GLOBAL base value, not the scope-aware view: a
            # set_options inside a scoped() block must restore the base on
            # exit, never promote the overlay value into the process dict
            self.old[k] = dict.__getitem__(OPTIONS, k)
        # pin provenance alongside the value (matters only to the
        # autotuner's may-I-adapt check, never to option values). A plain
        # setter call pins for the rest of the session; the context-manager
        # form unpins on exit along with restoring the value — once the
        # knob rides its built-in default again, it is back on the tuner's
        # "auto" surface (and library-internal with-blocks never leak pins)
        self._newly_explicit = set(kwargs) - _EXPLICIT_OPTIONS
        _EXPLICIT_OPTIONS.update(kwargs)
        OPTIONS.update(kwargs)

    def __enter__(self) -> None:
        return None

    def __exit__(self, *args: Any) -> None:
        OPTIONS.update(self.old)
        _EXPLICIT_OPTIONS.difference_update(self._newly_explicit)
