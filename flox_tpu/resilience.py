"""Resilient streaming: error taxonomy, retry/backoff, OOM slab-splitting,
and checkpoint/resume for the out-of-core executor.

The reference gets fault tolerance for free from dask's scheduler — a lost
worker's chunk reduction is simply re-executed (flox/dask.py tree-combine),
the classic MapReduce re-execution model. The streaming executor
(`streaming.py` + `pipeline.py`) has no scheduler to lean on: one transient
loader ``IOError``, one ``RESOURCE_EXHAUSTED`` on a too-large slab, or one
host preemption used to kill an hours-long reduction with nothing
recoverable. This module is the streaming equivalent of re-execution,
in three layers:

* **Error taxonomy** (:func:`classify_error`): every failure is ``transient``
  (IO hiccups — retried), ``oom`` (``XlaRuntimeError: RESOURCE_EXHAUSTED`` /
  ``MemoryError`` — the slab is split), or ``fatal`` (programming errors —
  surfaced immediately, never retried). The classifier is the single gate
  every retry path must consult; floxlint FLX006 flags `except Exception:`
  handlers in retry loops that bypass it.
* **Retry with exponential backoff + per-slab deadline**
  (:func:`call_with_retry`): wraps each slab's load+stage attempt
  (`pipeline.SlabStager`). Retries happen INSIDE the staging worker, so a
  flaky slab never poisons the other slabs queued in the prefetch pool;
  when retries exhaust, the ORIGINAL exception surfaces (not a wrapper).
* **Graceful OOM degradation** (:func:`dispatch_slab`): a slab step that
  raises a resource-exhausted error is re-staged as sub-slabs of half the
  span, padded to a power-of-two ladder — so each rung's step program is
  compiled once and every later split reuses it, and the base (full
  batch_len) step is never retraced.
* **Checkpoint/resume** (:class:`StreamCheckpointer`): every
  ``OPTIONS["stream_checkpoint_every"]`` processed slabs the carry state is
  ``jax.device_get`` into a host-side :class:`Snapshot` (registry
  ``_SNAPSHOTS``, cleared by ``cache.clear_all``), optionally spilled to an
  ``.npz`` under ``OPTIONS["stream_checkpoint_path"]``. A killed run
  re-invoked with the same arguments restores the snapshot and refolds only
  the remaining slabs — bit-identical to the uninterrupted run, because the
  device→host→device round-trip is exact and the remaining slabs fold in
  the same order.

Counters for all of the above (retries, backoff wall, splits, checkpoints)
flow into :class:`StreamCounters`, attached to the
``profiling.StreamReport`` each streaming pass emits.

The deterministic fault-injection harness that exercises every path here
lives in :mod:`flox_tpu.faults`.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

__all__ = [
    "TRANSIENT",
    "OOM",
    "FATAL",
    "DEVICE_LOST",
    "classify_error",
    "register_transient",
    "seed_backoff",
    "RetryPolicy",
    "call_with_retry",
    "StreamCounters",
    "dispatch_slab",
    "HighCardinalityOOMError",
    "Snapshot",
    "StreamCheckpointer",
    "device_restore",
]


class HighCardinalityOOMError(RuntimeError):
    """The OOM ladder bottomed out on an allocation the splitting cannot
    shrink: the dense per-group accumulators, sized by the label universe,
    not the slab. Raised in place of the bare re-raised OOM when the
    caller flagged the run as ngroups-dominated, carrying the actionable
    remedy (the sort / present-groups engine) in the message. Classified
    FATAL — re-splitting an accumulator-bound failure would loop the
    ladder for nothing."""

TRANSIENT = "transient"
OOM = "oom"
FATAL = "fatal"
#: the device (or its backend runtime) is gone — retrying the call cannot
#: help and splitting it cannot help; the serve plane reacts by failing
#: in-flight waiters, reinitializing the backend, and replaying its AOT
#: warmup manifest (serve/dispatcher.py device-loss recovery)
DEVICE_LOST = "device_lost"

# exception types retried as transient: IO and RPC hiccups. OSError subsumes
# IOError / TimeoutError / ConnectionError / BrokenPipeError — the loader-IO
# family (zarr, S3, NFS readers raise these for the recoverable cases).
# Programming errors (TypeError/ValueError/KeyError/...) are fatal by
# exclusion and surface immediately.
_TRANSIENT_TYPES: list[type] = [OSError]

# OSError subclasses that signal a configuration error, not weather: a wrong
# path or bad permissions can never succeed on retry, so burning the whole
# backoff budget on them is the exact swallow-a-bug hazard FLX006 polices.
# A store whose missing-key reads ARE transient (eventual consistency) can
# opt back in with register_transient(FileNotFoundError).
_NON_RECOVERABLE_OS: tuple[type, ...] = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
)

# jaxlib surfaces runtime failures as XlaRuntimeError with a gRPC-style
# status token; classify by name so no version-pinned import is needed
_RUNTIME_ERROR_NAMES = ("XlaRuntimeError", "JaxRuntimeError")
_OOM_TOKENS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")
_TRANSIENT_TOKENS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED")
# a dead chip surfaces as an XlaRuntimeError carrying one of these (PJRT
# wording varies by backend/version; faults.SimulatedDeviceLoss carries the
# first token so the chaos harness rides the same path as the real thing)
_DEVICE_LOSS_TOKENS = (
    "DEVICE_LOST", "device lost", "Device lost", "backend is dead",
    "device is in an invalid state",
)


def register_transient(exc_type: type) -> None:
    """Teach the classifier a loader-SDK exception type to retry (e.g. a
    cloud store's own ``ThrottlingError``). Process-global, additive."""
    if not (isinstance(exc_type, type) and issubclass(exc_type, BaseException)):
        raise TypeError(f"register_transient expects an exception type, got {exc_type!r}")
    if exc_type not in _TRANSIENT_TYPES:
        _TRANSIENT_TYPES.append(exc_type)


def classify_error(exc: BaseException) -> str:
    """``transient`` | ``oom`` | ``device_lost`` | ``fatal`` for one exception.

    The ONE gate every streaming retry/degradation path consults, so the
    transient-vs-fatal line cannot drift between them: transient errors are
    retried with backoff, oom errors trigger the slab split, device-loss
    errors trigger the serve plane's backend recovery, everything else
    (programming errors above all) raises immediately.

    A ``fatal`` verdict on the outermost exception is re-checked down the
    ``__cause__``/``__context__`` chain: a transient ``IOError`` that a
    wrapper (``asyncio.to_thread`` plumbing, a loader SDK's
    ``raise RuntimeError(...) from exc``) re-raised as a generic
    ``RuntimeError`` is still transient — misclassifying it fatal would
    turn an IO hiccup into a dead stream. Only fatal softens this way: an
    explicitly transient/oom outer classification is already the most
    actionable verdict and never consults the chain.
    """
    if isinstance(exc, HighCardinalityOOMError):
        # terminal by construction: its __cause__ IS an OOM, but the ladder
        # already proved splitting cannot shrink an ngroups-bound
        # allocation — the chain walk must not re-open the split loop
        return FATAL
    cls = _classify_one(exc)
    if cls != FATAL:
        return cls
    seen: set[int] = {id(exc)}
    queue: list[BaseException] = [exc]
    for _ in range(8):  # bounded: exception chains are short, cycles exist
        if not queue:
            break
        current = queue.pop(0)
        for link in (current.__cause__, current.__context__):
            if link is None or id(link) in seen:
                continue
            seen.add(id(link))
            inner = _classify_one(link)
            if inner != FATAL:
                return inner
            queue.append(link)
    return FATAL


def _classify_one(exc: BaseException) -> str:
    """Classification of one exception, ignoring its chain."""
    msg = str(exc)
    if isinstance(exc, HighCardinalityOOMError):
        # the ladder already proved splitting cannot help (the allocation
        # is ngroups-bound); OOM classification would re-enter the ladder
        return FATAL
    if isinstance(exc, MemoryError):
        # host-side slab allocation failure: splitting halves that too
        return OOM
    if type(exc).__name__ in _RUNTIME_ERROR_NAMES:
        if any(tok in msg for tok in _DEVICE_LOSS_TOKENS):
            return DEVICE_LOST
        if any(tok in msg for tok in _OOM_TOKENS):
            return OOM
        if any(tok in msg for tok in _TRANSIENT_TOKENS):
            return TRANSIENT
        return FATAL
    if isinstance(exc, RuntimeError) and any(
        tok in msg for tok in _DEVICE_LOSS_TOKENS
    ):
        # covers faults.SimulatedDeviceLoss and runtime wrappers that kept
        # the status token in the message
        return DEVICE_LOST
    if isinstance(exc, RuntimeError) and any(tok in msg for tok in _OOM_TOKENS):
        # covers faults.SimulatedOOM and any runtime wrapper that kept the
        # status token in the message
        return OOM
    if isinstance(exc, _NON_RECOVERABLE_OS) and not any(
        t is not OSError and isinstance(exc, t) for t in _TRANSIENT_TYPES
    ):
        return FATAL
    if isinstance(exc, tuple(_TRANSIENT_TYPES)):
        return TRANSIENT
    return FATAL


#: jitter source for the retry backoff — module-level so the fault harness
#: can pin it (:func:`seed_backoff`) and replay a chaos run's exact sleep
#: schedule; never used for anything load-bearing beyond scheduling
_BACKOFF_RNG = random.Random()


def seed_backoff(seed: Any = None) -> None:
    """Seed the backoff jitter source. The fault-injection tests pin it so
    a chaos run's retry schedule is reproducible; production leaves it
    unseeded (OS entropy) so prefetch workers de-synchronize."""
    _BACKOFF_RNG.seed(seed)


@dataclass(frozen=True)
class RetryPolicy:
    """Retry knobs for one stream, frozen at stream start.

    ``retries`` extra attempts per slab (so ``retries + 1`` total),
    ``backoff`` base sleep in seconds, ``timeout`` the per-slab deadline in
    seconds across ALL attempts+backoffs of that slab (0 = no deadline).

    Sleeps use **full jitter**: attempt ``k`` sleeps
    ``uniform(0, backoff * 2**k)``. Without it, every prefetch worker that
    hit the same transient fault (one flaky object store, N concurrent
    loads) retries at the same instant and they re-collide on every rung of
    the exponential ladder; the jitter spreads the retry herd across the
    whole window. Deterministic under the fault harness via
    :func:`seed_backoff`."""

    retries: int = 2
    backoff: float = 0.05
    timeout: float = 0.0

    @classmethod
    def from_options(cls) -> "RetryPolicy":
        from .options import OPTIONS

        return cls(
            retries=OPTIONS["stream_retries"],
            backoff=OPTIONS["stream_backoff"],
            timeout=OPTIONS["stream_slab_timeout"],
        )

    def delay(self, attempt: int) -> float:
        cap = self.backoff * (2.0**attempt)
        if cap <= 0:
            return 0.0
        # full jitter over the open interval: never exactly 0 (a zero sleep
        # would defeat the de-synchronization the jitter exists for) and
        # never the synchronized full cap
        u = _BACKOFF_RNG.random()
        return cap * (u if u > 0.0 else 0.5)


def _flight_on_fatal(exc: BaseException, what: str = "") -> None:
    """Dump the telemetry flight recorder for a fatal classification — the
    crash-forensics half of the taxonomy: transient errors retry, oom
    errors split, fatal ones leave a flight record and surface. A no-op
    when telemetry is off or no dump path is configured, and never raises
    (the original exception must surface unmasked)."""
    from . import telemetry

    telemetry.event("fatal", what=what, error=type(exc).__name__)
    telemetry.flight_dump(reason=f"fatal:{type(exc).__name__}" + (f" {what}" if what else ""))


def call_with_retry(
    fn: Callable[[], Any],
    *,
    policy: RetryPolicy,
    counters: "StreamCounters | None" = None,
    what: str = "",
) -> Any:
    """Run ``fn`` retrying transient failures with exponential backoff.

    Fatal and oom classifications raise immediately (oom belongs to the
    dispatch-side splitter, not the staging retry). When retries exhaust,
    the ORIGINAL exception is re-raised unchanged; when the per-slab
    deadline would be crossed by the next backoff, a ``TimeoutError``
    chains from it instead of sleeping past the budget.
    """
    deadline = time.monotonic() + policy.timeout if policy.timeout > 0 else None
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as exc:
            cls = classify_error(exc)
            if cls != TRANSIENT:
                if cls in (FATAL, DEVICE_LOST):
                    # a programming error (or a dead device) is about to
                    # surface: leave the flight record NOW, while the ring
                    # still holds the spans/events leading up to it (no-op
                    # unless FLOX_TPU_FLIGHT_RECORDER_PATH is configured)
                    _flight_on_fatal(exc, what=what)
                raise
            if attempt >= policy.retries:
                raise  # retries exhausted: surface the original exception
            delay = policy.delay(attempt)
            if deadline is not None and time.monotonic() + delay >= deadline:
                raise TimeoutError(
                    f"slab {what}: stream_slab_timeout of {policy.timeout:g}s "
                    f"exceeded after {attempt + 1} attempt(s)"
                ) from exc
            attempt += 1
            if counters is not None:
                counters.record_retry(delay)
            from . import telemetry

            if telemetry.enabled():
                telemetry.METRICS.inc("stream.retries")
                telemetry.event(
                    "retry", what=what, attempt=attempt,
                    delay_ms=round(delay * 1e3, 3), error=type(exc).__name__,
                )
            time.sleep(delay)


@dataclass
class StreamCounters:
    """Resilience counters for one streaming run, shared by the staging
    workers (retries), the dispatch guard (splits), and the checkpointer —
    and attached to every ``StreamReport`` the run emits (a multi-pass run
    like quantile reports the same cumulative object on each pass)."""

    retries: int = 0
    backoff_ms: float = 0.0
    oom_splits: int = 0
    checkpoints: int = 0
    #: stream-order slab cursor this run resumed from (None = fresh run)
    resumed_at: int | None = None
    #: phase resumed into (multi-pass runs: 0 = first pass)
    resumed_phase: int | None = None
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False, compare=False)

    def record_retry(self, delay_s: float) -> None:
        with self._lock:
            self.retries += 1
            self.backoff_ms += delay_s * 1e3

    def record_split(self) -> None:
        with self._lock:
            self.oom_splits += 1

    def record_checkpoint(self) -> None:
        with self._lock:
            self.checkpoints += 1


# ---------------------------------------------------------------------------
# graceful OOM degradation: halve + re-stage on a power-of-two ladder


def _pow2_ceil(x: int) -> int:
    return 1 << max(0, int(x) - 1).bit_length() if x > 1 else 1


def _ladder_half(length: int, quantum: int) -> int:
    """Sub-slab span for one split rung: half the span, rounded up to a
    power of two (so the re-staged shapes form a small reusable ladder —
    each rung's step program compiles once) and to the shard quantum (mesh
    slabs must keep equal per-device shards). When the quantum rounding
    would reach ``length`` itself (non-power-of-two device counts), fall
    back to the largest quantum multiple strictly below it — the ladder
    must keep descending as long as a legal split exists."""
    half = _pow2_ceil((length + 1) // 2)
    if quantum > 1:
        half = -(-half // quantum) * quantum
        if half >= length:
            half = ((length - 1) // quantum) * quantum
    return half


def dispatch_slab(
    apply_fn: Callable[[Any, Any], Any],
    carry: Any,
    sl: Any,
    *,
    stager: Any = None,
    counters: StreamCounters | None = None,
    shard_quantum: int = 1,
    reverse: bool = False,
    highcard_hint: str | None = None,
) -> Any:
    """Run one slab step — ``apply_fn(carry, slab) -> carry`` — with the
    fault-injection hook and graceful OOM degradation.

    On a resource-exhausted classification the slab's span is re-staged
    through ``stager`` (the same `pipeline.SlabStager` that staged it) as
    sub-slabs of half the span, padded to the power-of-two ladder, and
    folded through ``apply_fn`` one by one (in reverse span order for
    reversed streams, so scan carry semantics hold); a sub-slab that still
    OOMs splits again, down to single elements. ``stager=None`` disables
    splitting (the error propagates). Non-oom errors always propagate.

    ``highcard_hint``: set by callers whose accumulators are dense over an
    ngroups-dominated label universe (streaming runtime, size past
    ``sort_engine_min_groups``). When the ladder bottoms out — the span
    can no longer split, meaning the allocation that still fails is the
    accumulator, not the slab — the bare OOM is re-raised as a typed
    :class:`HighCardinalityOOMError` carrying the hint, which names the
    sort engine as the remedy.
    """
    from . import faults

    try:
        faults.poke(sl.start, sl.stop)
        return apply_fn(carry, sl)
    except Exception as exc:
        cls = classify_error(exc)
        if cls != OOM or stager is None:
            if cls in (FATAL, DEVICE_LOST):
                _flight_on_fatal(exc, what=f"[{sl.start}:{sl.stop})")
            raise
        return _split_dispatch(
            apply_fn, carry, sl.start, sl.stop, stager,
            counters=counters, quantum=shard_quantum, reverse=reverse, cause=exc,
            highcard_hint=highcard_hint,
        )


def _split_dispatch(
    apply_fn, carry, s, e, stager, *, counters, quantum, reverse, cause, depth=0,
    highcard_hint=None,
):
    from . import faults

    length = e - s
    half = _ladder_half(length, quantum)
    if length <= max(1, quantum) or half >= length or depth >= 48:
        # cannot split further: the failing allocation does not scale with
        # the span. On an ngroups-dominated run that is the dense
        # accumulator — surface the typed remedy instead of the bare OOM
        # (message deliberately free of OOM/status tokens so the
        # classifier cannot re-enter the ladder on it).
        if highcard_hint:
            raise HighCardinalityOOMError(
                "the slab-split ladder bottomed out at span "
                f"[{s}:{e}) but the step still exhausts device memory — "
                f"{highcard_hint}"
            ) from cause
        raise cause  # cannot split further: surface the original OOM
    if counters is not None:
        counters.record_split()
    from . import telemetry

    if telemetry.enabled():
        telemetry.METRICS.inc("stream.oom_splits")
        telemetry.event("oom-split", start=s, stop=e, half=half, depth=depth)
    spans = [(ss, min(ss + half, e)) for ss in range(s, e, half)]
    for ss, ee in reversed(spans) if reverse else spans:
        try:
            # staging inside the try: a sub-slab whose H2D transfer itself
            # exhausts memory splits again, same as a failing step
            sub = stager.stage_range(ss, ee, pad_to=half if stager.pad else None)
            faults.poke(ss, ee)
            carry = apply_fn(carry, sub)
        except Exception as exc:
            if classify_error(exc) != OOM:
                raise
            carry = _split_dispatch(
                apply_fn, carry, ss, ee, stager,
                counters=counters, quantum=quantum, reverse=reverse,
                cause=exc, depth=depth + 1, highcard_hint=highcard_hint,
            )
    return carry


# ---------------------------------------------------------------------------
# checkpoint / resume


@dataclass
class Snapshot:
    """One host-side stream checkpoint: the carry pytree (numpy leaves,
    ``jax.device_get`` of the device state — exact bytes), the stream-order
    slab cursor it covers, and the phase for multi-pass runs (quantile:
    0 = count pass, 1+i = bit pass i)."""

    key: tuple
    phase: int
    slabs_done: int
    payload: Any


#: in-memory snapshot registry, keyed by the stream identity tuple.
#: Registered in cache.clear_all with the other module-level caches.
_SNAPSHOTS: dict[tuple, Snapshot] = {}


class StreamCheckpointer:
    """Periodic host-side snapshots of a streaming run's carry state.

    Disabled (every method a no-op) unless
    ``OPTIONS["stream_checkpoint_every"] > 0``. The stream identity key is
    derived from the run's semantic shape (kind, aggregation name, n,
    batch_len, size, a codes fingerprint, the mesh layout) so a re-invoked
    identical call finds its predecessor's snapshot; with
    ``OPTIONS["stream_checkpoint_path"]`` set, snapshots also spill to an
    ``.npz`` (written atomically via rename) and survive the process — the
    cross-process resume path. ``done()`` removes the snapshot once the run
    completes, so a later identical call starts fresh.

    Resume is bit-identical: ``device_get``/``device_put`` round-trips are
    exact, and the remaining slabs refold from the snapshot in the same
    stream order as the uninterrupted run.
    """

    def __init__(
        self,
        key: tuple | None,
        *,
        every: int | None = None,
        path: str | None = None,
        counters: StreamCounters | None = None,
    ) -> None:
        from .options import OPTIONS

        self.every = OPTIONS["stream_checkpoint_every"] if every is None else every
        self.path = OPTIONS["stream_checkpoint_path"] if path is None else path
        self.key = key
        self.counters = counters
        self.enabled = key is not None and self.every > 0
        self._ticks = 0

    @classmethod
    def for_stream(
        cls,
        *,
        kind: str,
        name: str,
        n: int,
        batch_len: int,
        size: int,
        codes: np.ndarray,
        lead_shape: tuple = (),
        mesh_key: Any = None,
        extra: tuple = (),
        data_probe: Any = None,
        counters: StreamCounters | None = None,
        enabled: bool = True,
    ) -> "StreamCheckpointer":
        from .options import OPTIONS

        if not enabled or OPTIONS["stream_checkpoint_every"] <= 0:
            # the fingerprints are skipped entirely when checkpointing is
            # off — the disabled path costs nothing per stream
            return cls(None, counters=counters)
        fp = hashlib.blake2b(
            np.ascontiguousarray(codes).tobytes(), digest_size=8
        ).hexdigest()
        # data tripwire: the entry points pass their one probe slab (the
        # loader's first element), so re-running after the data VALUES
        # changed at position 0 misses the stale snapshot instead of
        # silently folding old state into new data. A change that leaves
        # element 0 intact still matches — a cursor checkpoint can only
        # ever assume the input is immutable for the run's lifetime
        # (documented); this catches the common fixed-and-reran case.
        probe_fp = None
        if data_probe is not None:
            probe_fp = hashlib.blake2b(
                np.ascontiguousarray(np.asarray(data_probe)).tobytes(), digest_size=8
            ).hexdigest()
        key = (
            kind, str(name), int(n), int(batch_len), int(size),
            tuple(lead_shape), fp, probe_fp, mesh_key, tuple(extra),
        )
        return cls(key, counters=counters)

    def restore(self) -> Snapshot | None:
        """The latest snapshot for this stream identity (in-memory registry
        first, then the spill file), or None for a fresh run."""
        if not self.enabled:
            return None
        snap = _SNAPSHOTS.get(self.key)
        if snap is None and self.path:
            snap = _load_snapshot(self._file(), self.key)
            if snap is not None:
                _SNAPSHOTS[self.key] = snap
        if snap is not None and self.counters is not None:
            self.counters.resumed_at = snap.slabs_done
            self.counters.resumed_phase = snap.phase
        if snap is not None:
            from . import telemetry

            if telemetry.enabled():
                telemetry.METRICS.inc("stream.resumes")
                telemetry.event(
                    "stream-resume", slabs_done=snap.slabs_done, phase=snap.phase
                )
        return snap

    def tick(
        self, payload_fn: Callable[[], Any], *, slabs_done: int, phase: int = 0
    ) -> None:
        """Count one processed slab; snapshot every ``every`` ticks.
        ``payload_fn`` is only called when a snapshot is actually taken."""
        if not self.enabled:
            return
        self._ticks += 1
        if self._ticks % self.every:
            return
        self.save(payload_fn(), slabs_done=slabs_done, phase=phase)

    def save(self, payload: Any, *, slabs_done: int, phase: int = 0) -> None:
        if not self.enabled:
            return
        import jax

        host = jax.device_get(payload)
        snap = Snapshot(key=self.key, phase=phase, slabs_done=slabs_done, payload=host)
        _SNAPSHOTS[self.key] = snap
        if self.path:
            _dump_snapshot(self._file(), snap)
        if self.counters is not None:
            self.counters.record_checkpoint()
        from . import telemetry

        if telemetry.enabled():
            d2h = sum(
                int(np.asarray(leaf).nbytes)
                for leaf in jax.tree_util.tree_leaves(host)
            )
            telemetry.METRICS.inc("stream.checkpoints")
            telemetry.METRICS.inc("bytes.d2h", d2h)
            telemetry.event("checkpoint", slabs_done=slabs_done, phase=phase, bytes=d2h)

    def done(self) -> None:
        """The run completed: drop its snapshot (registry + spill file) so
        the next identical call starts fresh instead of resuming at the end."""
        if not self.enabled:
            return
        _SNAPSHOTS.pop(self.key, None)
        if self.path:
            try:
                os.unlink(self._file())
            except OSError:
                pass

    def _file(self) -> str:
        path = str(self.path)
        if path.endswith(".npz"):
            return path
        h = hashlib.blake2b(repr(self.key).encode(), digest_size=8).hexdigest()
        return os.path.join(path, f"flox-tpu-stream-{h}.npz")


def _dump_snapshot(path: str, snap: Snapshot) -> None:
    import jax

    from .store import write_checksummed_npz

    leaves, treedef = jax.tree_util.tree_flatten(snap.payload)
    arrays = {f"leaf{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    meta = pickle.dumps((snap.key, snap.phase, snap.slabs_done, treedef))
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    # the store's checksummed segment format (per-array blake2b digests,
    # format-versioned header, tmp+fsync+rename), so a torn or bit-flipped
    # spill is DETECTED at restore instead of loading silently wrong state
    write_checksummed_npz(
        path,
        {"__meta__": np.frombuffer(meta, dtype=np.uint8), **arrays},
        {"kind": "stream-checkpoint"},
        kind="checkpoint",
    )


def _load_snapshot(path: str, key: tuple) -> Snapshot | None:
    """Read a spilled snapshot; None when missing, corrupt, or for a
    different stream identity — a damaged spill warns (and counts on
    ``stream.checkpoint_corrupt``) before restarting the stream fresh. The
    meta block (including the jax treedef) is a pickle WE wrote — the spill
    path is operator-controlled state, not untrusted input."""
    import jax

    from .store import StoreCorruptionError, read_checksummed_npz

    try:
        z, _ = read_checksummed_npz(path)
    except FileNotFoundError:
        return None
    except StoreCorruptionError as exc:
        # a checkpoint that fails its checksums (torn write, bit rot, or a
        # pre-checksum legacy spill) must mean "fresh run", loudly
        import warnings

        from . import telemetry

        warnings.warn(
            f"stream checkpoint {os.path.basename(path)} is corrupt or "
            f"unreadable; restarting the stream fresh ({exc})",
            RuntimeWarning,
            stacklevel=2,
        )
        telemetry.METRICS.inc("stream.checkpoint_corrupt")
        return None
    try:
        skey, phase, done, treedef = pickle.loads(z["__meta__"].tobytes())
        if skey != key:
            return None
        leaves = [z[f"leaf{i}"] for i in range(treedef.num_leaves)]
        payload = jax.tree_util.tree_unflatten(treedef, leaves)
    except Exception:
        # the contract is "a corrupt or mismatched spill is ignored, never
        # trusted": unpickling a stale treedef across a jax upgrade can
        # raise essentially anything (AttributeError, ModuleNotFoundError,
        # TypeError, BadZipFile...), and every one of them must mean
        # "fresh run", not a crash at restore time
        return None
    return Snapshot(key=key, phase=phase, slabs_done=done, payload=payload)


def device_restore(payload: Any, *, mesh: Any = None, spec_entry: Any = None) -> Any:
    """Host snapshot payload -> device state, matching the layout the
    streaming loop would have produced: plain device arrays single-device,
    ``NamedSharding(mesh, P(spec_entry))`` on the leading axis for the
    per-device mesh accumulators (replicated state passes ``mesh=None`` —
    jit re-replicates plain arrays on entry)."""
    import jax
    import jax.numpy as jnp

    if mesh is None:
        return jax.tree.map(jnp.asarray, payload)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(spec_entry))
    return jax.tree.map(lambda h: jax.device_put(h, sharding), payload)
