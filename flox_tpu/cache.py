"""Host-side memoization (parity: /root/reference/flox/cache.py:3-12).

The reference memoizes chunk-boundary analysis with a cachey cache keyed by
dask tokens, and exposes the cache object so callers (its asv benchmarks,
debugging sessions) can clear it between runs. Here the cached inputs are
hashable tuples (label fingerprints, shard counts), so plain LRUs and
dicts suffice; ``memoize`` keeps the reference's decorator name and
``clear_all`` is the analogue of ``flox.cache.cache.clear()``.
"""

from __future__ import annotations

import functools

memoize = functools.lru_cache(maxsize=512)


def stats() -> dict:
    """Current entry counts of every named host-side cache (plus the
    kernel-bundle LRU's hit/miss counters) — the cache panel of the
    telemetry layer (``telemetry.profile_call`` embeds this, and a bench
    row showing ``bundle_lru.misses`` climbing across same-shaped calls is
    a retrace storm caught red-handed)."""
    from .autotune import _AUTOTUNE_CACHE
    from .cohorts import _COHORTS_CACHE
    from .core import _jitted_bundle
    from .factorize import _FACTORIZE_CACHE
    from .parallel.mapreduce import _PROGRAM_CACHE
    from .parallel.scan import _SCAN_CACHE
    from .streaming import _STEP_CACHE

    info = _jitted_bundle.cache_info()
    return {
        "cohorts": len(_COHORTS_CACHE),
        "factorize": len(_FACTORIZE_CACHE),
        "mesh_programs": len(_PROGRAM_CACHE),
        "scan_programs": len(_SCAN_CACHE),
        "stream_steps": len(_STEP_CACHE),
        "autotune": len(_AUTOTUNE_CACHE),
        "bundle_lru": {
            "size": info.currsize, "hits": info.hits, "misses": info.misses
        },
    }


def clear_all() -> None:
    """Drop every host-side cache: cohort-detection memos, compiled mesh
    program/scan caches, and the jitted kernel-bundle LRU — and reset the
    telemetry metrics registry, whose cache-hit/miss and compile counters
    describe exactly the state being dropped (a benchmark that clears
    between timing rounds must not carry stale counts across them). The
    analogue of the reference's ``flox.cache.cache.clear()`` (its asv
    benchmarks clear between timing rounds; ``benchmarks.py`` here does the
    same)."""
    from .autotune import _AUTOTUNE_CACHE, _AUTOTUNE_STATE
    from .cohorts import _COHORTS_CACHE
    from .core import _jitted_bundle
    from .factorize import _FACTORIZE_CACHE, _FACTORIZE_CACHE_BYTES
    from .kernels import (
        _PALLAS_COMPILE_PROBE,
        _PALLAS_MINMAX_COMPILE_PROBE,
        _PALLAS_MINMAX_PROBE_RESULT,
        _PALLAS_PROBE_RESULT,
        _PALLAS_SCAN_COMPILE_PROBE,
        _PALLAS_SCAN_PROBE_RESULT,
    )
    from .parallel.mapreduce import _PROGRAM_CACHE
    from .parallel.scan import _SCAN_CACHE
    from .pipeline import _DONATION_OK
    from .resilience import _SNAPSHOTS
    from .streaming import _STEP_CACHE
    from .telemetry import METRICS

    _COHORTS_CACHE.clear()
    _FACTORIZE_CACHE.clear()
    _FACTORIZE_CACHE_BYTES[0] = 0
    _PROGRAM_CACHE.clear()
    _SCAN_CACHE.clear()
    _STEP_CACHE.clear()
    _DONATION_OK.clear()
    _SNAPSHOTS.clear()
    # pallas one-time probe memos (floxlint FLX008: every runtime-accreted
    # module-level cache must be reachable from here) — the next reduction
    # after a clear re-validates the backend, which is exactly the fresh
    # state a between-rounds clear promises
    _PALLAS_PROBE_RESULT.clear()
    _PALLAS_COMPILE_PROBE.clear()
    _PALLAS_MINMAX_PROBE_RESULT.clear()
    _PALLAS_MINMAX_COMPILE_PROBE.clear()
    _PALLAS_SCAN_PROBE_RESULT.clear()
    _PALLAS_SCAN_COMPILE_PROBE.clear()
    # autotune measurement store + its counters/lazy-load flag: clearing
    # returns the tuner to the unloaded state, so the next consult reloads
    # the persisted file (or runs plain heuristics when no path is set) —
    # every accessor reads the state dict through .get() with a default,
    # making the empty dict the reset state
    _AUTOTUNE_CACHE.clear()
    _AUTOTUNE_STATE.clear()
    _jitted_bundle.cache_clear()
    METRICS.reset()
