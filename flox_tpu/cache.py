"""Host-side memoization (parity: /root/reference/flox/cache.py:3-12).

The reference memoizes chunk-boundary analysis with a cachey cache keyed by
dask tokens, and exposes the cache object so callers (its asv benchmarks,
debugging sessions) can clear it between runs. Here the cached inputs are
hashable tuples (label fingerprints, shard counts), so plain LRUs and
dicts suffice; ``memoize`` keeps the reference's decorator name and
``clear_all`` is the analogue of ``flox.cache.cache.clear()``.
"""

from __future__ import annotations

import functools

memoize = functools.lru_cache(maxsize=512)


def clear_all() -> None:
    """Drop every host-side cache: cohort-detection memos, compiled mesh
    program/scan caches, and the jitted kernel-bundle LRU. The analogue of
    the reference's ``flox.cache.cache.clear()`` (its asv benchmarks clear
    between timing rounds; ``benchmarks.py`` here does the same)."""
    from .cohorts import _COHORTS_CACHE
    from .core import _jitted_bundle
    from .factorize import _FACTORIZE_CACHE, _FACTORIZE_CACHE_BYTES
    from .parallel.mapreduce import _PROGRAM_CACHE
    from .parallel.scan import _SCAN_CACHE
    from .pipeline import _DONATION_OK
    from .resilience import _SNAPSHOTS
    from .streaming import _STEP_CACHE

    _COHORTS_CACHE.clear()
    _FACTORIZE_CACHE.clear()
    _FACTORIZE_CACHE_BYTES[0] = 0
    _PROGRAM_CACHE.clear()
    _SCAN_CACHE.clear()
    _STEP_CACHE.clear()
    _DONATION_OK.clear()
    _SNAPSHOTS.clear()
    _jitted_bundle.cache_clear()
