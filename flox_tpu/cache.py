"""Host-side memoization (parity: /root/reference/flox/cache.py:3-12).

The reference memoizes chunk-boundary analysis with a cachey cache keyed by
dask tokens. Here the cached inputs are hashable tuples (label fingerprints,
shard counts), so a plain LRU suffices; a `memoize` name is kept so the call
sites read the same.
"""

from __future__ import annotations

import functools

memoize = functools.lru_cache(maxsize=512)
