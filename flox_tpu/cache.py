"""Host-side memoization (parity: /root/reference/flox/cache.py:3-12).

The reference memoizes chunk-boundary analysis with a cachey cache keyed by
dask tokens, and exposes the cache object so callers (its asv benchmarks,
debugging sessions) can clear it between runs. Here the cached inputs are
hashable tuples (label fingerprints, shard counts), so plain LRUs and
dicts suffice; ``memoize`` keeps the reference's decorator name and
``clear_all`` is the analogue of ``flox.cache.cache.clear()``.
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Any, Iterator

memoize = functools.lru_cache(maxsize=512)


class LRUCache:
    """A bounded dict with least-recently-used eviction, for the compiled
    program caches.

    The mesh ``_PROGRAM_CACHE`` and streaming ``_STEP_CACHE`` used to
    wholesale ``.clear()`` past 256 entries — under sustained mixed traffic
    that evicts every HOT compiled program the moment one cold key tips the
    bound, and the next request for each recompiles from scratch (seconds
    of XLA wall per program). LRU keeps the hot set: a ``get`` hit renews
    the entry, inserts evict only the single stalest key, and the eviction
    count is visible in :func:`stats` so a serving process can alarm on
    thrash instead of discovering it as tail latency.

    The mapping surface mirrors what callers already used on the plain
    dicts (``get`` / ``[]=`` / ``len`` / ``clear`` / ``items`` / ``in``);
    a lock keeps renew-on-read safe under the serving dispatcher's
    executor threads.
    """

    def __init__(self, maxsize: int = 256) -> None:
        if maxsize < 1:
            raise ValueError(f"LRUCache maxsize must be >= 1, got {maxsize}")
        self.maxsize = maxsize
        self.evictions = 0
        self._data: OrderedDict = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key: Any, default: Any = None) -> Any:
        with self._lock:
            try:
                self._data.move_to_end(key)
            except KeyError:
                return default
            return self._data[key]

    def __getitem__(self, key: Any) -> Any:
        with self._lock:
            self._data.move_to_end(key)
            return self._data[key]

    def __setitem__(self, key: Any, value: Any) -> None:
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.maxsize:
                self._data.popitem(last=False)
                self.evictions += 1

    def __contains__(self, key: Any) -> bool:
        with self._lock:
            return key in self._data

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def __iter__(self) -> Iterator:
        with self._lock:
            return iter(list(self._data))

    def keys(self) -> list:
        with self._lock:
            return list(self._data.keys())

    def values(self) -> list:
        with self._lock:
            return list(self._data.values())

    def items(self) -> list:
        with self._lock:
            return list(self._data.items())

    def pop(self, key: Any, *default: Any) -> Any:
        with self._lock:
            return self._data.pop(key, *default)

    def clear(self) -> None:
        """Drop every entry (eviction counter intact: it counts capacity
        evictions, not deliberate clears)."""
        with self._lock:
            self._data.clear()


def stats() -> dict:
    """Current entry counts of every named host-side cache (plus the
    kernel-bundle LRU's hit/miss counters) — the cache panel of the
    telemetry layer (``telemetry.profile_call`` embeds this, and a bench
    row showing ``bundle_lru.misses`` climbing across same-shaped calls is
    a retrace storm caught red-handed)."""
    from .autotune import _AUTOTUNE_CACHE
    from .cohorts import _COHORTS_CACHE
    from .core import _jitted_bundle
    from .costmodel import _CARD_REGISTRY
    from .factorize import _FACTORIZE_CACHE
    from .fusion import _FUSED_PROGRAM_CACHE
    from .kernels import _PRESENT_CACHE
    from .parallel.mapreduce import _PROGRAM_CACHE
    from .parallel.scan import _SCAN_CACHE
    from .profiling import capture_active
    from .serve.aot import _MANIFEST_MEMO
    from .serve.breaker import breaker_stats
    from .serve.dispatcher import _BATCH_REGISTRY, _COALESCE_CACHE, _PENDING_REGISTRY
    from .serve.registry import registry_stats
    from .serve.stores import stores_stats
    from .slo import slo_stats
    from .streaming import _STEP_CACHE
    from .telemetry import (
        FLIGHT_RECORDER,
        cost_by_dataset,
        cost_by_program,
        cost_by_tenant,
        hbm_by_program,
    )

    info = _jitted_bundle.cache_info()
    return {
        # per-program-key cost ledger (telemetry.observe_cost): dispatches /
        # device_ms / bytes / compiles / hbm peak / last slow trace per
        # compiled-program key, plus the per-tenant axis the serve layer
        # feeds — read through the locked accessors, never the raw table
        "cost_by_program": cost_by_program(),
        "cost_by_tenant": cost_by_tenant(),
        # per-resident-dataset axis of the same ledger: fed only by serve
        # dispatches that referenced a registry entry ("dataset": name)
        "cost_by_dataset": cost_by_dataset(),
        # per-program-key peak HBM: the hbm_peak column of the ledger, kept
        # as its own view (the operator's answer to "which compiled program
        # is eating the chip")
        "hbm_by_program": hbm_by_program(),
        # compiled-program card registry (flox_tpu/costmodel.py): one card
        # per (program label, input signature) holding the analytical
        # flops/bytes/footprint the roofline join divides by
        "costmodel_cards": len(_CARD_REGISTRY),
        "flight_recorder": len(FLIGHT_RECORDER),
        # the on-demand capture guard: whether a jax.profiler capture is
        # running right now (profiling.start_capture / /debug/profile)
        "profile_capture_active": capture_active() is not None,
        "cohorts": len(_COHORTS_CACHE),
        "factorize": len(_FACTORIZE_CACHE),
        # present-group tables of the sort engine (kernels.present_groups):
        # one sorted-unique table per distinct code-content fingerprint
        "present_tables": len(_PRESENT_CACHE),
        "mesh_programs": len(_PROGRAM_CACHE),
        "scan_programs": len(_SCAN_CACHE),
        "stream_steps": len(_STEP_CACHE),
        "fused_programs": len(_FUSED_PROGRAM_CACHE),
        "autotune": len(_AUTOTUNE_CACHE),
        # capacity evictions of the compiled-program LRUs: a serving
        # process alarms on these climbing (program-cache thrash shows up
        # here first, as recompiles second, as tail latency last)
        "evictions": {
            "mesh_programs": _PROGRAM_CACHE.evictions,
            "stream_steps": _STEP_CACHE.evictions,
            "fused_programs": _FUSED_PROGRAM_CACHE.evictions,
        },
        # serving layer: queued/in-flight requests, open coalescing
        # entries + micro-batches, and AOT programs pending manifest save
        "serve_pending": len(_PENDING_REGISTRY),
        "serve_coalesce": len(_COALESCE_CACHE),
        "serve_batches": len(_BATCH_REGISTRY),
        "serve_aot_manifest": len(_MANIFEST_MEMO),
        # resident dataset registry: entry/byte/pin counts, the HBM budget
        # in force, and deliberate budget evictions (the runbook alarm)
        "registry": registry_stats(),
        # durable aggregation stores: open-store count, per-store
        # generations, host-carry bytes, device-cache occupancy
        "stores": stores_stats(),
        # per-program circuit breakers: entry counts per state plus the
        # open/half-open detail (which program labels are being fast-failed
        # and how long their cooldowns have left)
        "serve_breakers": breaker_stats(),
        # SLO plane: spec in force, window-snapshot depth, alert counts per
        # state, canary probe/failure totals — a snapshot, never a fresh
        # evaluation (stats must not move the alert state machine)
        "slo": slo_stats(),
        "bundle_lru": {
            "size": info.currsize, "hits": info.hits, "misses": info.misses
        },
    }


def clear_all() -> None:
    """Drop every host-side cache: cohort-detection memos, compiled mesh
    program/scan caches, and the jitted kernel-bundle LRU — and reset the
    telemetry metrics registry, whose cache-hit/miss and compile counters
    describe exactly the state being dropped (a benchmark that clears
    between timing rounds must not carry stale counts across them). The
    analogue of the reference's ``flox.cache.cache.clear()`` (its asv
    benchmarks clear between timing rounds; ``benchmarks.py`` here does the
    same)."""
    from .autotune import _AUTOTUNE_CACHE, _AUTOTUNE_STATE
    from .cohorts import _COHORTS_CACHE
    from .core import _jitted_bundle
    from .costmodel import _CARD_LABELS, _CARD_REGISTRY
    from .factorize import _FACTORIZE_CACHE, _FACTORIZE_CACHE_BYTES
    from .fusion import _FUSED_PROGRAM_CACHE
    from .kernels import (
        _PALLAS_COMPILE_PROBE,
        _PALLAS_MINMAX_COMPILE_PROBE,
        _PALLAS_MINMAX_PROBE_RESULT,
        _PALLAS_MULTISTAT_COMPILE_PROBE,
        _PALLAS_MULTISTAT_PROBE_RESULT,
        _PALLAS_PROBE_RESULT,
        _PALLAS_RADIXBIN_COMPILE_PROBE,
        _PALLAS_RADIXBIN_PROBE_RESULT,
        _PALLAS_SCAN_COMPILE_PROBE,
        _PALLAS_SCAN_PROBE_RESULT,
        _PRESENT_CACHE,
    )
    from .parallel.mapreduce import _PROGRAM_CACHE
    from .parallel.scan import _SCAN_CACHE
    from .pipeline import _DONATION_OK, _PREFETCH_INFLIGHT
    from .profiling import _CAPTURE_STATE
    from .resilience import _SNAPSHOTS
    from .serve.aot import _MANIFEST_MEMO
    from .serve.breaker import _BREAKER_REGISTRY
    from .serve.dispatcher import _BATCH_REGISTRY, _COALESCE_CACHE, _PENDING_REGISTRY
    from .streaming import _STEP_CACHE
    from .telemetry import (
        FLIGHT_RECORDER,
        METRICS,
        _COST_LEDGER,
        _TAIL_REGISTRY,
        _TENANT_LABELS,
    )

    _COHORTS_CACHE.clear()
    _FACTORIZE_CACHE.clear()
    _FACTORIZE_CACHE_BYTES[0] = 0
    _PROGRAM_CACHE.clear()
    _SCAN_CACHE.clear()
    _STEP_CACHE.clear()
    _FUSED_PROGRAM_CACHE.clear()
    _DONATION_OK.clear()
    _SNAPSHOTS.clear()
    # serving layer (flox_tpu/serve/): admission/pending table, coalescing
    # + micro-batch tables, and the AOT warmup-manifest memo. Safe while a
    # dispatcher is live: open batches hold direct references to their own
    # entries, so a clear only prevents NEW requests from joining them.
    _PENDING_REGISTRY.clear()
    _COALESCE_CACHE.clear()
    _BATCH_REGISTRY.clear()
    _MANIFEST_MEMO.clear()
    # resident dataset registry: registry.clear() drops _DATASET_REGISTRY
    # and resets the eviction counter + gauges; in-flight dispatches keep
    # their direct references, so a clear only unpublishes names
    from .serve import registry as serve_registry

    serve_registry.clear()
    # durable store table: stores.clear() drops _STORE_TABLE and resets its
    # gauges; on-disk WAL/segment state is durable and untouched — a later
    # reference reopens (= recovers) it
    from .serve import stores as serve_stores

    serve_stores.clear()
    # circuit-breaker state resets with the program caches it shadows: a
    # cleared process has no failure history, so no breaker stays open
    _BREAKER_REGISTRY.clear()
    # pallas one-time probe memos (floxlint FLX008: every runtime-accreted
    # module-level cache must be reachable from here) — the next reduction
    # after a clear re-validates the backend, which is exactly the fresh
    # state a between-rounds clear promises
    _PALLAS_PROBE_RESULT.clear()
    _PALLAS_COMPILE_PROBE.clear()
    _PALLAS_MINMAX_PROBE_RESULT.clear()
    _PALLAS_MINMAX_COMPILE_PROBE.clear()
    _PALLAS_SCAN_PROBE_RESULT.clear()
    _PALLAS_SCAN_COMPILE_PROBE.clear()
    _PALLAS_MULTISTAT_PROBE_RESULT.clear()
    _PALLAS_MULTISTAT_COMPILE_PROBE.clear()
    _PALLAS_RADIXBIN_PROBE_RESULT.clear()
    _PALLAS_RADIXBIN_COMPILE_PROBE.clear()
    # sort-engine present-group tables (content-fingerprint keyed)
    _PRESENT_CACHE.clear()
    # autotune measurement store + its counters/lazy-load flag: clearing
    # returns the tuner to the unloaded state, so the next consult reloads
    # the persisted file (or runs plain heuristics when no path is set) —
    # every accessor reads the state dict through .get() with a default,
    # making the empty dict the reset state
    _AUTOTUNE_CACHE.clear()
    _AUTOTUNE_STATE.clear()
    _jitted_bundle.cache_clear()
    # observability plane (flox_tpu/telemetry.py + profiling.py +
    # pipeline.py): the flight-recorder ring, the per-trace parked
    # tail-detail buffers, the per-program/per-tenant cost ledger (HBM
    # attribution absorbed into it), the on-demand-capture guard, and the
    # prefetch-occupancy gauge counter reset with the metrics they
    # annotate. METRICS.reset() also drops the histograms' exemplar slots
    # — they live inside the registry's histogram state.
    # cost-model plane (flox_tpu/costmodel.py): the compiled-program card
    # registry and its label index reset with the ledger they annotate
    _CARD_REGISTRY.clear()
    _CARD_LABELS.clear()
    FLIGHT_RECORDER.clear()
    _TAIL_REGISTRY.clear()
    _COST_LEDGER.clear()
    _TENANT_LABELS.clear()
    _CAPTURE_STATE.clear()
    _PREFETCH_INFLIGHT[0] = 0
    METRICS.reset()
    # SLO plane (flox_tpu/slo.py): slo.clear() drops the burn-rate window
    # snapshot ring, the alert state table, the canary probe ledger, the
    # freshness tick ledger and the parsed-spec cache (its body references
    # _SNAPSHOT_RING / _ALERT_TABLE / _CANARY_LEDGER / _FRESHNESS_LEDGER /
    # _SPEC_CACHE directly for floxlint FLX008) — alert state must not
    # outlive the counters (just reset above) it judged
    from . import slo as slo_plane

    slo_plane.clear()
