"""Profiling hooks (auxiliary subsystem; SURVEY.md §5).

The reference has no built-in profiler beyond debug logging — profiling is
external (asv, snakeviz). On TPU the native tool is ``jax.profiler``; this
module provides the thin wrappers so users can capture a trace of a grouped
reduction without learning the jax API.
"""

from __future__ import annotations

import contextlib
import logging
import time

logger = logging.getLogger("flox_tpu")

__all__ = ["trace", "annotate", "timed"]


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a jax profiler trace (view with TensorBoard / xprof).

    >>> with flox_tpu.profiling.trace("/tmp/flox-trace"):  # doctest: +SKIP
    ...     groupby_reduce(...)
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("profiler trace written to %s", logdir)


def annotate(name: str):
    """Named region that shows up inside profiler traces."""
    import jax

    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def timed(label: str):
    """Wall-clock log line for a block (host-side; includes dispatch)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        logger.info("%s took %.3f ms", label, (time.perf_counter() - t0) * 1e3)
