"""Profiling hooks (auxiliary subsystem; SURVEY.md §5).

The reference has no built-in profiler beyond debug logging — profiling is
external (asv, snakeviz). On TPU the native tool is ``jax.profiler``; this
module provides the thin wrappers so users can capture a trace of a grouped
reduction without learning the jax API, plus the streaming-pipeline
instrumentation (:func:`stream_monitor`): every ``streaming_groupby_*``
call emits one :class:`StreamReport` of per-slab load/stage/wait/dispatch
timings from which the prefetch overlap is read directly.

On-demand capture (ISSUE 9): a serving replica cannot wrap its hot loop in
a ``with trace(...)`` block after the fact — the moment an operator wants a
device profile is exactly while the process is misbehaving. The capture
surface (:func:`start_capture`) starts a ``jax.profiler`` trace into a
rotated directory under ``OPTIONS["profile_dir"]`` and stops it after N
seconds on a timer thread, one capture at a time; it is reachable over
HTTP (``/debug/profile?seconds=N`` on the metrics endpoint), over the
serve protocol (``{"op": "profile"}``) and via SIGUSR1
(:func:`install_capture_signal`), and never raises into the serve loop.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator

logger = logging.getLogger("flox_tpu.profiling")

__all__ = [
    "trace",
    "annotate",
    "timed",
    "stream_monitor",
    "StreamReport",
    "CaptureBusyError",
    "CaptureUnavailableError",
    "capture_active",
    "install_capture_signal",
    "start_capture",
]


class CaptureBusyError(RuntimeError):
    """A capture is already running — one at a time (the profiler is a
    process-global singleton; HTTP answers 409)."""


class CaptureUnavailableError(RuntimeError):
    """No capture is possible: the backend has no profiler, or no capture
    root is configured (HTTP answers 501)."""


def _default_logdir() -> Any:
    from .options import OPTIONS

    return OPTIONS["profile_dir"]


@contextlib.contextmanager
def trace(logdir: str | None = None):
    """Capture a jax profiler trace (view with TensorBoard / xprof).

    ``logdir`` defaults to ``OPTIONS["profile_dir"]`` (env
    ``FLOX_TPU_PROFILE_DIR``) — the same root the on-demand capture surface
    rotates under; with neither configured this raises ``ValueError``. A
    backend without a working profiler warns and no-ops instead of raising:
    the block still runs, only the trace is missing.

    >>> with flox_tpu.profiling.trace("/tmp/flox-trace"):  # doctest: +SKIP
    ...     groupby_reduce(...)
    """
    import jax

    if logdir is None:
        logdir = _default_logdir()
    if logdir is None:
        raise ValueError(
            "profiling.trace() needs a logdir: pass one explicitly or set "
            "OPTIONS['profile_dir'] (env FLOX_TPU_PROFILE_DIR)"
        )
    logdir = str(logdir)
    try:
        jax.profiler.start_trace(logdir)
    except Exception as exc:  # noqa: BLE001 — a profiler-less backend must
        # not take the profiled workload down with it: warn and run untraced
        logger.warning("profiler unavailable, running untraced: %s", exc)
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
            logger.info("profiler trace written to %s", logdir)
        except Exception as exc:  # noqa: BLE001 — same contract as start
            logger.warning("profiler stop failed: %s", exc)


# ---------------------------------------------------------------------------
# on-demand capture: bounded, rotated, one at a time
# ---------------------------------------------------------------------------


#: capture-state guard: ``{"active": {...}}`` while a capture runs (dir /
#: seconds / started), plus the rotation sequence counter. One capture at a
#: time — the jax profiler is process-global. Every accessor reads through
#: ``.get()`` with a default, so the empty dict is the reset state
#: (registered in cache.clear_all; a clear during a live capture only
#: forgets the guard — the timer thread still stops the profiler).
_CAPTURE_STATE: dict[str, Any] = {}
_CAPTURE_LOCK = threading.Lock()


def capture_active() -> dict | None:
    """A copy of the live capture's info (dir/seconds/started), or ``None``."""
    with _CAPTURE_LOCK:
        active = _CAPTURE_STATE.get("active")
        return dict(active) if active else None


def _rotate_captures(root: Any, keep: int) -> None:
    """Delete the oldest ``capture-*`` dirs so at most ``keep - 1`` remain
    before a new one is created — an operator poking ``/debug/profile`` in
    a loop must never fill the disk. Timestamped names sort chronologically."""
    import os
    import shutil

    try:
        entries = sorted(
            e for e in os.listdir(str(root)) if e.startswith("capture-")
        )
    except OSError:
        return
    excess = len(entries) - (keep - 1)
    for stale in entries[:excess] if excess > 0 else []:
        shutil.rmtree(os.path.join(str(root), stale), ignore_errors=True)


def start_capture(seconds: float = 5.0, root: Any = None) -> str:
    """Start an on-chip profiler capture; stop it after ``seconds``.

    The capture lands in a fresh ``capture-<stamp>-<seq>`` dir under
    ``root`` (default ``OPTIONS["profile_dir"]``), with old captures
    rotated out past ``OPTIONS["profile_keep"]``. Returns the capture dir
    immediately — the stop runs on a daemon timer thread, so the caller
    (the HTTP handler, the serve loop, a signal handler's helper thread)
    never blocks behind the capture window. Raises
    :class:`CaptureBusyError` while another capture runs,
    :class:`CaptureUnavailableError` when no root is configured or the
    backend has no working profiler, ``ValueError`` for a bad window.
    """
    import os

    from . import telemetry
    from .options import OPTIONS

    seconds = float(seconds)
    if not 0 < seconds <= 3600:
        raise ValueError(f"capture window must be in (0, 3600] seconds, got {seconds}")
    if root is None:
        root = OPTIONS["profile_dir"]
    if root is None:
        raise CaptureUnavailableError(
            "no capture root configured: set OPTIONS['profile_dir'] "
            "(env FLOX_TPU_PROFILE_DIR)"
        )
    with _CAPTURE_LOCK:
        if _CAPTURE_STATE.get("active"):
            raise CaptureBusyError(
                f"capture already running in {_CAPTURE_STATE['active']['dir']}"
            )
        seq = _CAPTURE_STATE.get("seq", 0) + 1
        _CAPTURE_STATE["seq"] = seq
        os.makedirs(str(root), exist_ok=True)
        _rotate_captures(root, int(OPTIONS["profile_keep"]))
        stamp = time.strftime("%Y%m%d-%H%M%S")
        capture_dir = os.path.join(str(root), f"capture-{stamp}-{seq:03d}")
        try:
            import jax

            jax.profiler.start_trace(capture_dir)
        except Exception as exc:  # noqa: BLE001 — no profiler on this backend
            raise CaptureUnavailableError(f"profiler unavailable: {exc}") from exc
        # dispatch-mark snapshot: the finished capture is stamped with
        # exactly the program labels (and card digests) dispatched during
        # the window — the join from a capture dir back to /debug/costs
        # and /debug/programs rows (best-effort; never blocks the start)
        try:
            from .costmodel import dispatch_marks

            marks = dispatch_marks()
        except Exception:  # noqa: BLE001 — stamping is best-effort by contract
            marks = {}
        _CAPTURE_STATE["active"] = {
            "dir": capture_dir, "seconds": seconds, "started": time.time(),
            "marks": marks,
        }

    def _finish() -> None:
        try:
            import jax

            jax.profiler.stop_trace()
            logger.info("on-demand capture written to %s", capture_dir)
        except Exception as exc:  # noqa: BLE001 — stopping is best-effort;
            # the guard must clear either way or no capture ever runs again
            logger.warning("on-demand capture stop failed: %s", exc)
        with _CAPTURE_LOCK:
            active = _CAPTURE_STATE.get("active", {})
            marks_then = active.get("marks") if active.get("dir") == capture_dir else None
            if active.get("dir") == capture_dir:
                _CAPTURE_STATE.pop("active", None)
        if marks_then is not None:
            # stamp the capture with the programs dispatched inside the
            # window (cumulative ledger dispatches minus the start marks),
            # each with its card digest — documented in the capture
            # runbook. A vanished guard (cache.clear_all mid-window) has
            # no baseline: skip rather than attribute history to the window
            try:
                from .costmodel import stamp_capture

                stamp_capture(capture_dir, marks_then)
            except Exception:  # noqa: BLE001 — stamping never breaks a capture
                pass
        telemetry.count("profile.captures")
        telemetry.event("profile.capture", dir=capture_dir, seconds=seconds)

    timer = threading.Timer(seconds, _finish)
    timer.daemon = True
    timer.start()
    telemetry.count("profile.capture_starts")
    return capture_dir


def install_capture_signal() -> None:
    """SIGUSR1 -> a 5-second on-demand capture into the configured root.

    Signal-safe: the handler only spawns a daemon thread (no profiler work,
    no locks in the interrupted frame) and never raises — a busy or
    unconfigured capture is a log line, not a crash. No-op on platforms
    without SIGUSR1 or off the main thread."""
    import signal

    signum = getattr(signal, "SIGUSR1", None)
    if signum is None:
        return

    def _capture_bg() -> None:
        try:
            start_capture(seconds=5.0)
        except (CaptureBusyError, CaptureUnavailableError, ValueError) as exc:
            logger.warning("SIGUSR1 capture not started: %s", exc)

    def _handler(signum: int, frame: Any) -> None:
        threading.Thread(
            target=_capture_bg, name="flox-tpu-capture", daemon=True
        ).start()

    try:
        signal.signal(signum, _handler)
    except (ValueError, OSError):  # not the main thread / exotic platform
        return


def annotate(name: str):
    """Named region that shows up inside profiler traces."""
    import jax

    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def timed(label: str):
    """Wall-clock log line for a block (host-side; includes dispatch)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        logger.info("%s took %.3f ms", label, (time.perf_counter() - t0) * 1e3)


@dataclass
class StreamReport:
    """Per-slab pipeline timings for one streaming pass.

    ``slabs`` holds the :class:`flox_tpu.pipeline.Slab` records in
    consumption order; each carries ``load_ms`` (loader IO), ``stage_ms``
    (pad + device_put), ``wait_ms`` (time the consumer thread was blocked
    waiting for the slab — with prefetch off this IS load+stage, with
    prefetch on it is only the unhidden remainder) and ``dispatch_ms``
    (consumer-side step dispatch, including any throttle sync).

    ``counters`` is the run's ``resilience.StreamCounters``: retry /
    backoff-wait / OOM-split / checkpoint totals, plus the resume cursor
    when the run restored from a snapshot. A multi-pass run (streaming
    quantile) shares ONE counters object across its passes, so each pass's
    report shows the cumulative values."""

    label: str = ""
    prefetch: int = 0
    nbatches: int = 0
    wall_ms: float = 0.0
    slabs: list = field(default_factory=list)
    counters: Any = None

    @property
    def load_ms(self) -> float:
        return sum(s.load_ms for s in self.slabs)

    @property
    def stage_ms(self) -> float:
        return sum(s.stage_ms for s in self.slabs)

    @property
    def wait_ms(self) -> float:
        return sum(s.wait_ms for s in self.slabs)

    @property
    def dispatch_ms(self) -> float:
        return sum(s.dispatch_ms for s in self.slabs)

    @property
    def overlap_fraction(self) -> float:
        """Share of the staging wall (load+stage) hidden off the consumer's
        critical path: 0.0 when every staged byte was waited for inline
        (prefetch off), approaching 1.0 when the pipeline kept staging
        entirely behind dispatch/compute."""
        staged = self.load_ms + self.stage_ms
        if staged <= 0.0:
            return 0.0
        return min(1.0, max(0.0, 1.0 - self.wait_ms / staged))

    @property
    def retries(self) -> int:
        return self.counters.retries if self.counters is not None else 0

    @property
    def backoff_ms(self) -> float:
        return self.counters.backoff_ms if self.counters is not None else 0.0

    @property
    def oom_splits(self) -> int:
        return self.counters.oom_splits if self.counters is not None else 0

    @property
    def checkpoints(self) -> int:
        return self.counters.checkpoints if self.counters is not None else 0

    @property
    def resumed_at(self):
        return self.counters.resumed_at if self.counters is not None else None

    def summary(self) -> str:
        line = (
            f"stream-pipeline [{self.label}] {len(self.slabs)}/{self.nbatches} "
            f"slab(s) prefetch={self.prefetch}: wall {self.wall_ms:.1f} ms, "
            f"load {self.load_ms:.1f} ms, stage {self.stage_ms:.1f} ms, "
            f"wait {self.wait_ms:.1f} ms, dispatch {self.dispatch_ms:.1f} ms, "
            f"overlap {self.overlap_fraction:.0%}"
        )
        if self.retries:
            line += f", retries {self.retries} (backoff {self.backoff_ms:.0f} ms)"
        if self.oom_splits:
            line += f", oom-splits {self.oom_splits}"
        if self.checkpoints:
            line += f", checkpoints {self.checkpoints}"
        if self.resumed_at is not None:
            line += f", resumed@{self.resumed_at}"
        return line


# active stream_monitor collectors (consumer-thread only: reports are
# appended by the stream_slabs generator after each pass completes)
_MONITORS: list[list[StreamReport]] = []


@contextlib.contextmanager
def stream_monitor() -> Iterator[list[StreamReport]]:
    """Collect the :class:`StreamReport` of every streaming pass in scope.

    >>> from flox_tpu import profiling, streaming_groupby_reduce
    >>> with profiling.stream_monitor() as reports:  # doctest: +SKIP
    ...     streaming_groupby_reduce(loader, by, func="nanmean")
    >>> reports[0].overlap_fraction  # doctest: +SKIP
    """
    reports: list[StreamReport] = []
    _MONITORS.append(reports)
    try:
        yield reports
    finally:
        _MONITORS.remove(reports)


def record_stream(report: Any) -> None:
    """Deliver one finished pass to every active monitor (and the log)."""
    for collector in _MONITORS:
        collector.append(report)
    logger.info("%s", report.summary())
