"""Profiling hooks (auxiliary subsystem; SURVEY.md §5).

The reference has no built-in profiler beyond debug logging — profiling is
external (asv, snakeviz). On TPU the native tool is ``jax.profiler``; this
module provides the thin wrappers so users can capture a trace of a grouped
reduction without learning the jax API, plus the streaming-pipeline
instrumentation (:func:`stream_monitor`): every ``streaming_groupby_*``
call emits one :class:`StreamReport` of per-slab load/stage/wait/dispatch
timings from which the prefetch overlap is read directly.
"""

from __future__ import annotations

import contextlib
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Iterator

logger = logging.getLogger("flox_tpu.profiling")

__all__ = ["trace", "annotate", "timed", "stream_monitor", "StreamReport"]


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a jax profiler trace (view with TensorBoard / xprof).

    >>> with flox_tpu.profiling.trace("/tmp/flox-trace"):  # doctest: +SKIP
    ...     groupby_reduce(...)
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("profiler trace written to %s", logdir)


def annotate(name: str):
    """Named region that shows up inside profiler traces."""
    import jax

    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def timed(label: str):
    """Wall-clock log line for a block (host-side; includes dispatch)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        logger.info("%s took %.3f ms", label, (time.perf_counter() - t0) * 1e3)


@dataclass
class StreamReport:
    """Per-slab pipeline timings for one streaming pass.

    ``slabs`` holds the :class:`flox_tpu.pipeline.Slab` records in
    consumption order; each carries ``load_ms`` (loader IO), ``stage_ms``
    (pad + device_put), ``wait_ms`` (time the consumer thread was blocked
    waiting for the slab — with prefetch off this IS load+stage, with
    prefetch on it is only the unhidden remainder) and ``dispatch_ms``
    (consumer-side step dispatch, including any throttle sync).

    ``counters`` is the run's ``resilience.StreamCounters``: retry /
    backoff-wait / OOM-split / checkpoint totals, plus the resume cursor
    when the run restored from a snapshot. A multi-pass run (streaming
    quantile) shares ONE counters object across its passes, so each pass's
    report shows the cumulative values."""

    label: str = ""
    prefetch: int = 0
    nbatches: int = 0
    wall_ms: float = 0.0
    slabs: list = field(default_factory=list)
    counters: Any = None

    @property
    def load_ms(self) -> float:
        return sum(s.load_ms for s in self.slabs)

    @property
    def stage_ms(self) -> float:
        return sum(s.stage_ms for s in self.slabs)

    @property
    def wait_ms(self) -> float:
        return sum(s.wait_ms for s in self.slabs)

    @property
    def dispatch_ms(self) -> float:
        return sum(s.dispatch_ms for s in self.slabs)

    @property
    def overlap_fraction(self) -> float:
        """Share of the staging wall (load+stage) hidden off the consumer's
        critical path: 0.0 when every staged byte was waited for inline
        (prefetch off), approaching 1.0 when the pipeline kept staging
        entirely behind dispatch/compute."""
        staged = self.load_ms + self.stage_ms
        if staged <= 0.0:
            return 0.0
        return min(1.0, max(0.0, 1.0 - self.wait_ms / staged))

    @property
    def retries(self) -> int:
        return self.counters.retries if self.counters is not None else 0

    @property
    def backoff_ms(self) -> float:
        return self.counters.backoff_ms if self.counters is not None else 0.0

    @property
    def oom_splits(self) -> int:
        return self.counters.oom_splits if self.counters is not None else 0

    @property
    def checkpoints(self) -> int:
        return self.counters.checkpoints if self.counters is not None else 0

    @property
    def resumed_at(self):
        return self.counters.resumed_at if self.counters is not None else None

    def summary(self) -> str:
        line = (
            f"stream-pipeline [{self.label}] {len(self.slabs)}/{self.nbatches} "
            f"slab(s) prefetch={self.prefetch}: wall {self.wall_ms:.1f} ms, "
            f"load {self.load_ms:.1f} ms, stage {self.stage_ms:.1f} ms, "
            f"wait {self.wait_ms:.1f} ms, dispatch {self.dispatch_ms:.1f} ms, "
            f"overlap {self.overlap_fraction:.0%}"
        )
        if self.retries:
            line += f", retries {self.retries} (backoff {self.backoff_ms:.0f} ms)"
        if self.oom_splits:
            line += f", oom-splits {self.oom_splits}"
        if self.checkpoints:
            line += f", checkpoints {self.checkpoints}"
        if self.resumed_at is not None:
            line += f", resumed@{self.resumed_at}"
        return line


# active stream_monitor collectors (consumer-thread only: reports are
# appended by the stream_slabs generator after each pass completes)
_MONITORS: list[list[StreamReport]] = []


@contextlib.contextmanager
def stream_monitor() -> Iterator[list[StreamReport]]:
    """Collect the :class:`StreamReport` of every streaming pass in scope.

    >>> from flox_tpu import profiling, streaming_groupby_reduce
    >>> with profiling.stream_monitor() as reports:  # doctest: +SKIP
    ...     streaming_groupby_reduce(loader, by, func="nanmean")
    >>> reports[0].overlap_fraction  # doctest: +SKIP
    """
    reports: list[StreamReport] = []
    _MONITORS.append(reports)
    try:
        yield reports
    finally:
        _MONITORS.remove(reports)


def record_stream(report: Any) -> None:
    """Deliver one finished pass to every active monitor (and the log)."""
    for collector in _MONITORS:
        collector.append(report)
    logger.info("%s", report.summary())
