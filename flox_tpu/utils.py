"""Duck-array and null-handling utilities (L0).

Parity target: /root/reference/flox/xrutils.py (isnull/notnull at
xrutils.py:149-187, duck-array predicates at xrutils.py:85-146), re-thought
for a JAX world: the load-bearing split here is *host arrays* (numpy, where
labels/metadata live and object/datetime dtypes are legal) vs *device arrays*
(jax, always numeric, traced under jit).
"""

from __future__ import annotations

import importlib
from collections.abc import Iterable
from typing import Any

import numpy as np

from . import dtypes


def module_available(name: str) -> bool:
    try:
        importlib.import_module(name)
    except ImportError:
        return False
    return True


HAS_XARRAY = module_available("xarray")
HAS_MATPLOTLIB = module_available("matplotlib")


def fmt_bytes(n: float) -> str:
    """Human size for guard messages: GiB above 1, MiB below."""
    return f"{n / 2**30:.1f} GiB" if n >= 2**30 else f"{n / 2**20:.1f} MiB"


def is_jax_array(x: Any) -> bool:
    import jax

    return isinstance(x, jax.Array)


def x64_enabled() -> bool:
    """Whether jax is configured for 64-bit dtypes (True when jax is absent)."""
    try:
        import jax

        return bool(jax.config.jax_enable_x64)
    except ImportError:  # pragma: no cover
        return True


def is_duck_array(value: Any) -> bool:
    if isinstance(value, np.ndarray):
        return True
    return (
        hasattr(value, "ndim")
        and hasattr(value, "shape")
        and hasattr(value, "dtype")
        and (hasattr(value, "__array_function__") or hasattr(value, "__array_namespace__"))
    )


def asarray_host(x: Any) -> np.ndarray:
    """Materialize on host as numpy (labels, metadata, finalize-side work)."""
    if isinstance(x, np.ndarray):
        return x
    if is_jax_array(x):
        return np.asarray(x)
    return np.asarray(x)

def asarray_device(x: Any):
    """Put on device as a jnp array, viewing datetimes as int64."""
    import jax.numpy as jnp

    if is_jax_array(x):
        return x
    x = np.asarray(x)
    if dtypes.is_datetime_like(x.dtype):
        x = x.view("int64")
    from . import telemetry

    if telemetry.enabled():
        # host -> device staging bytes (the streaming pipeline's device_put
        # counts its own in pipeline.SlabStager)
        telemetry.METRICS.inc("bytes.h2d", int(x.nbytes))
    return jnp.asarray(x)


def isnull(data: Any):
    """Elementwise missing-mask valid for any dtype (host or device).

    Parity: xrutils.isnull (xrutils.py:149-168) — NaN for floats/complex,
    NaT for datetimes, never-null for ints/bools; object arrays checked via
    pandas on host.
    """
    if is_jax_array(data):
        import jax.numpy as jnp

        if jnp.issubdtype(data.dtype, jnp.floating) or jnp.issubdtype(
            data.dtype, jnp.complexfloating
        ):
            return jnp.isnan(data)
        return jnp.zeros(data.shape, dtype=bool)
    data = np.asarray(data)
    dtype = data.dtype
    if np.issubdtype(dtype, np.floating) or np.issubdtype(dtype, np.complexfloating):
        return np.isnan(data)
    if dtypes.is_datetime_like(dtype):
        return np.isnat(data)
    if dtype.kind == "O":
        import pandas as pd

        return pd.isnull(data)
    return np.zeros(data.shape, dtype=bool)


def notnull(data: Any):
    return ~isnull(data)


def is_scalar(value: Any) -> bool:
    return np.ndim(value) == 0 and not isinstance(value, (list, tuple, dict, set))


def normalize_axis_tuple(axis: int | Iterable[int], ndim: int) -> tuple[int, ...]:
    if np.isscalar(axis):
        axis = (int(axis),)  # type: ignore[arg-type]
    return tuple(sorted(ax % ndim for ax in axis))  # type: ignore[union-attr]


def moveaxis_to_end(array, axes: tuple[int, ...]):
    """Move ``axes`` to the trailing positions, preserving their order."""
    keep = [ax for ax in range(array.ndim) if ax not in axes]
    return array.transpose(keep + list(axes)), tuple(keep)


def reapply_nonfinite(sums, nan_c, pos_c, neg_c, *, skipna: bool = False):
    """Re-apply IEEE non-finite propagation to segment sums computed on
    zero-filled data with NaN/+inf/-inf marker counts (shared by the MXU
    GEMM and Pallas segment-sum paths so their semantics cannot drift).

    ``skipna=True`` treats NaN as absent (the fused nan-aggregation path
    sums over raw, unmasked data): zeroed NaNs simply do not contribute,
    and only the ±inf rules apply."""
    import jax.numpy as jnp

    poison = (pos_c > 0) & (neg_c > 0)
    if not skipna:
        poison = poison | (nan_c > 0)
    return jnp.where(
        poison,
        jnp.asarray(jnp.nan, sums.dtype),
        jnp.where(
            pos_c > 0,
            jnp.asarray(jnp.inf, sums.dtype),
            jnp.where(neg_c > 0, jnp.asarray(-jnp.inf, sums.dtype), sums),
        ),
    )


def is_nan_fill(v) -> bool:
    """True only for genuine float/complex NaN fills. NaT (datetime64 /
    timedelta64) answers True to np.isnan but must NOT trigger float
    promotion — timestamps would lose ns precision through float64."""
    if isinstance(v, (np.datetime64, np.timedelta64)):
        return False
    try:
        return bool(np.isnan(v))
    except (TypeError, ValueError):
        return False
