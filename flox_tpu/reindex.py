"""Reindexing: align a per-group axis to a target index (L3).

Parity target: /root/reference/flox/reindex.py — ``reindex_``
(reindex.py:160-216), ``ReindexStrategy``/``ReindexArrayType``
(reindex.py:23-89).

On the device paths this framework *always* reduces into a dense axis over
``expected_groups`` (static shapes are load-bearing for XLA and
collectives), so device results never need reindexing. This host-side
``reindex_`` serves the remaining cases: aligning host results of a
discovery-mode reduction (expected_groups=None) to a user index, and the
xarray adapter's coordinate alignment.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from enum import Enum, auto
from typing import Any

import numpy as np
import pandas as pd

from . import dtypes

__all__ = ["reindex_", "reindex_sparse_coo", "HostCOO", "ReindexStrategy", "ReindexArrayType"]


class ReindexArrayType(Enum):
    """Which array type holds the reindexed result (reindex.py:23-50).

    SPARSE_COO targets enormous group spaces (the reference's NWM-county
    case, reindex.py:106-157): instead of materializing a dense
    ``(…, len(to))`` array, only the found groups' columns are stored —
    as a jax ``BCOO`` (device-ready, zero fill) or a host COO (non-zero
    fill values).
    """

    AUTO = auto()
    NUMPY = auto()
    SPARSE_COO = auto()


@dataclass(frozen=True)
class ReindexStrategy:
    """Whether to reindex blockwise (per shard) and into what array type
    (reindex.py:53-89). On the mesh runtime ``blockwise=True`` is implicit:
    each shard's intermediates are dense over expected_groups.

    Accepted by ``groupby_reduce(reindex=...)``: ``blockwise=True/None``
    with a dense ``array_type`` maps to the implicit dense behavior;
    ``array_type=SPARSE_COO`` routes the host result leg through
    :func:`reindex_sparse_coo`; ``blockwise=False`` with a dense array
    type is a no-op eagerly and for ``cohorts``/``blockwise`` (whose
    combines are already label-aligned) and raises only for mesh
    ``map-reduce``, pointing at the
    ``set_options(dense_intermediate_bytes_max=...)`` ceiling that
    provides the capability instead — see core.py.
    """

    blockwise: bool | None = None
    array_type: ReindexArrayType = ReindexArrayType.AUTO

    def __post_init__(self):
        # parity: reference reindex.py:69-73 — a sparse blockwise reindex
        # makes no sense (each block would densify on combine)
        if self.blockwise is True and self.array_type not in (
            ReindexArrayType.AUTO,
            ReindexArrayType.NUMPY,
        ):
            raise ValueError("Setting reindex.blockwise=True not allowed for non-numpy array type.")

    def set_blockwise_for_numpy(self) -> "ReindexStrategy":
        """Resolve ``blockwise=None`` to ``True`` for the numpy container
        path (parity: reference reindex.py:75-76, which mutates in place).

        Returns a NEW strategy via :func:`dataclasses.replace` — the frozen
        instance is never mutated, so its by-value hash stays stable and an
        instance already used as a dict/set/cache key keeps meaning what it
        meant. Call sites rebind: ``strategy = strategy.set_blockwise_for_numpy()``.
        """
        if self.blockwise is None:
            return dataclasses.replace(self, blockwise=True)
        return self


@dataclass
class HostCOO:
    """Minimal host-side COO result for non-zero fill values, the shape the
    reference gets from pydata sparse (reindex.py:106-157): last axis
    sparse, everything before it dense.

    ``columns`` are the populated positions along the last axis; ``data``
    is ``(…, len(columns))``.
    """

    columns: np.ndarray
    data: np.ndarray
    shape: tuple[int, ...]
    fill_value: Any

    @property
    def nnz(self) -> int:
        return int(self.data.size)

    def todense(self) -> np.ndarray:
        out = np.full(self.shape, self.fill_value, dtype=self.data.dtype)
        out[..., self.columns] = self.data
        return out


def _is_nan_scalar(v) -> bool:
    try:
        return np.ndim(v) == 0 and bool(np.isnan(v))
    except (TypeError, ValueError):
        return False


def reindex_sparse_coo(array, from_: pd.Index, to: pd.Index, *, fill_value=None, dtype=None):
    """Reindex the trailing group axis into a sparse container.

    For huge ``to`` spaces (e.g. every county id) the dense result would be
    mostly fill; store only the found groups. Returns a jax ``BCOO`` when
    the fill is zero — directly consumable by further jax computation — and
    a :class:`HostCOO` otherwise (BCOO's implicit value is always 0).
    Parity: reindex_pydata_sparse_coo (reference reindex.py:106-157).
    """
    if not isinstance(from_, pd.Index):
        from_ = pd.Index(from_)
    if not isinstance(to, pd.Index):
        to = pd.Index(to)
    array = np.asarray(array)
    if dtype is not None:
        array = array.astype(dtype, copy=False)

    idx = to.get_indexer(from_)  # target position of each source column
    mask = idx >= 0
    needs_fill = len(to) > int(mask.sum())
    if (fill_value is dtypes.NA or _is_nan_scalar(fill_value)) and array.dtype.kind not in "fc":
        # a NaN-ish fill on int data promotes, exactly like the dense path
        promoted, _ = dtypes.maybe_promote(array.dtype)
        array = array.astype(promoted, copy=False)
    if fill_value in (dtypes.INF, dtypes.NINF, dtypes.NA):
        fill_value = dtypes.get_fill_value(array.dtype, fill_value)
    if fill_value is None:
        if needs_fill:
            raise ValueError("Filling is required. fill_value cannot be None.")
        fill_value = 0
    shape = array.shape[:-1] + (len(to),)
    cols = idx[mask]
    data = array[..., mask]

    is_zero = False
    try:
        is_zero = not np.any(np.asarray(fill_value))
    except (TypeError, ValueError):
        pass
    from .utils import x64_enabled

    if not is_zero or (data.dtype.itemsize >= 8 and not x64_enabled()):
        # non-zero fill (BCOO's implicit value is always 0), OR a 64-bit
        # result that jnp.asarray would silently truncate with x64 off —
        # keep the exact host container either way
        return HostCOO(columns=cols, data=data, shape=shape, fill_value=fill_value)

    from jax.experimental import sparse as jsparse
    import jax.numpy as jnp

    # BCOO layout: leading dims batch, trailing group axis sparse
    nbatch = array.ndim - 1
    indices = jnp.broadcast_to(
        jnp.asarray(cols, dtype=jnp.int32).reshape((1,) * nbatch + (-1, 1)),
        array.shape[:-1] + (cols.shape[0], 1),
    )
    return jsparse.BCOO(
        (jnp.asarray(data), indices), shape=shape,
        indices_sorted=bool(np.all(np.diff(cols) > 0)), unique_indices=True,
    )


def reindex_(
    array,
    from_: pd.Index,
    to: pd.Index,
    *,
    fill_value: Any = None,
    axis: int = -1,
    promote: bool = False,
    array_type: ReindexArrayType = ReindexArrayType.AUTO,
) -> np.ndarray:
    """Gather ``array``'s group axis from ``from_`` order into ``to`` order.

    Missing target groups are filled with ``fill_value`` (sentinels resolved
    against the array dtype). Parity: reindex_numpy (reindex.py:92-103);
    ``array_type=SPARSE_COO`` routes to :func:`reindex_sparse_coo`.
    """
    if array_type == ReindexArrayType.SPARSE_COO:
        if axis != -1:
            raise NotImplementedError("sparse reindex supports axis=-1 only")
        return reindex_sparse_coo(array, from_, to, fill_value=fill_value)
    if not isinstance(from_, pd.Index):
        from_ = pd.Index(from_)
    if not isinstance(to, pd.Index):
        to = pd.Index(to)
    array = np.asarray(array)

    idx = from_.get_indexer(to)
    missing = idx < 0

    if fill_value in (dtypes.INF, dtypes.NINF):
        # representable without promotion (iinfo extremes for ints)
        fill_value = dtypes.get_fill_value(array.dtype, fill_value)
    elif fill_value is dtypes.NA or fill_value is None:
        if missing.any() or promote:
            promoted, na = dtypes.maybe_promote(array.dtype)
            array = array.astype(promoted, copy=False)
            fill_value = dtypes.get_fill_value(promoted, dtypes.NA)
        else:
            fill_value = 0  # unused
    out = np.take(array, np.where(missing, 0, idx), axis=axis)
    if missing.any():
        shape = [1] * out.ndim
        shape[axis] = len(to)
        mask = np.broadcast_to(missing.reshape(shape), out.shape)
        out = np.where(mask, fill_value, out)
    return out
