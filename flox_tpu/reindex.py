"""Reindexing: align a per-group axis to a target index (L3).

Parity target: /root/reference/flox/reindex.py — ``reindex_``
(reindex.py:160-216), ``ReindexStrategy``/``ReindexArrayType``
(reindex.py:23-89).

On the device paths this framework *always* reduces into a dense axis over
``expected_groups`` (static shapes are load-bearing for XLA and
collectives), so device results never need reindexing. This host-side
``reindex_`` serves the remaining cases: aligning host results of a
discovery-mode reduction (expected_groups=None) to a user index, and the
xarray adapter's coordinate alignment.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum, auto
from typing import Any

import numpy as np
import pandas as pd

from . import dtypes

__all__ = ["reindex_", "ReindexStrategy", "ReindexArrayType"]


class ReindexArrayType(Enum):
    """Which array type holds the reindexed result (reindex.py:23-50).

    The reference offers sparse.COO for enormous group spaces; that backend
    is unavailable here, so AUTO always resolves to NUMPY (device results
    are dense by construction).
    """

    AUTO = auto()
    NUMPY = auto()
    SPARSE_COO = auto()


@dataclass(frozen=True)
class ReindexStrategy:
    """Whether to reindex blockwise (per shard) and into what array type
    (reindex.py:53-89). On the mesh runtime ``blockwise=True`` is implicit:
    each shard's intermediates are dense over expected_groups."""

    blockwise: bool | None = None
    array_type: ReindexArrayType = ReindexArrayType.AUTO

    def __post_init__(self):
        if self.array_type == ReindexArrayType.SPARSE_COO:
            raise NotImplementedError(
                "sparse.COO reindexing requires the 'sparse' package, which is "
                "not available in this build."
            )


def reindex_(
    array,
    from_: pd.Index,
    to: pd.Index,
    *,
    fill_value: Any = None,
    axis: int = -1,
    promote: bool = False,
) -> np.ndarray:
    """Gather ``array``'s group axis from ``from_`` order into ``to`` order.

    Missing target groups are filled with ``fill_value`` (sentinels resolved
    against the array dtype). Parity: reindex_numpy (reindex.py:92-103).
    """
    if not isinstance(from_, pd.Index):
        from_ = pd.Index(from_)
    if not isinstance(to, pd.Index):
        to = pd.Index(to)
    array = np.asarray(array)

    idx = from_.get_indexer(to)
    missing = idx < 0

    if fill_value in (dtypes.INF, dtypes.NINF):
        # representable without promotion (iinfo extremes for ints)
        fill_value = dtypes.get_fill_value(array.dtype, fill_value)
    elif fill_value is dtypes.NA or fill_value is None:
        if missing.any() or promote:
            promoted, na = dtypes.maybe_promote(array.dtype)
            array = array.astype(promoted, copy=False)
            fill_value = dtypes.get_fill_value(promoted, dtypes.NA)
        else:
            fill_value = 0  # unused
    out = np.take(array, np.where(missing, 0, idx), axis=axis)
    if missing.any():
        shape = [1] * out.ndim
        shape[axis] = len(to)
        mask = np.broadcast_to(missing.reshape(shape), out.shape)
        out = np.where(mask, fill_value, out)
    return out
