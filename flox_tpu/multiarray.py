"""MultiArray: a tuple-of-arrays that flows through the reduction machinery
as one value (parity: /root/reference/flox/multiarray.py:9-97, used by the
single-pass variance path, aggregations.py:348-451).

TPU-native twist: registered as a JAX pytree, so a MultiArray intermediate
(the variance triple ``(sum_sq_dev, sum, count)``) passes transparently
through ``jit`` / ``shard_map`` and collectives apply leaf-wise.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

try:  # register as pytree when jax is importable
    import jax.tree_util as _jtu
except ImportError:  # pragma: no cover
    _jtu = None


class MultiArray:
    __slots__ = ("arrays",)

    def __init__(self, arrays) -> None:
        self.arrays = tuple(arrays)

    def __len__(self) -> int:
        return len(self.arrays)

    def __iter__(self):
        return iter(self.arrays)

    def __getitem__(self, i):
        return self.arrays[i]

    @property
    def shape(self):
        return self.arrays[0].shape

    @property
    def ndim(self):
        return self.arrays[0].ndim

    @property
    def dtype(self):
        return self.arrays[0].dtype

    def astype(self, dtype, **kwargs) -> "MultiArray":
        return MultiArray(tuple(a.astype(dtype, **kwargs) for a in self.arrays))

    def reshape(self, *shape) -> "MultiArray":
        return MultiArray(tuple(a.reshape(*shape) for a in self.arrays))

    def squeeze(self, axis=None) -> "MultiArray":
        return MultiArray(tuple(a.squeeze(axis) for a in self.arrays))

    def map(self, fn: Callable[[Any], Any]) -> "MultiArray":
        return MultiArray(tuple(fn(a) for a in self.arrays))

    def __repr__(self) -> str:
        return f"MultiArray({self.arrays!r})"


def concatenate(arrays, axis=0):
    """Concatenate supporting MultiArray leaves (multiarray.py:60-71 parity)."""
    first = arrays[0]
    if isinstance(first, MultiArray):
        return MultiArray(
            tuple(np.concatenate([a.arrays[i] for a in arrays], axis=axis) for i in range(len(first)))
        )
    return np.concatenate(arrays, axis=axis)


if _jtu is not None:
    _jtu.register_pytree_node(
        MultiArray,
        lambda ma: (ma.arrays, None),
        lambda _, children: MultiArray(children),
    )


# ---------------------------------------------------------------------------
# PresentGroups: the sparse (present-groups) intermediate of the sort engine
# ---------------------------------------------------------------------------


def _combine_identity(op: str, dtype):
    """Identity element of a combine op — what a group absent from one side
    of a merge contributes. Mirrors kernels.minmax_identity for min/max
    (multiarray sits below kernels, so the few lines are restated rather
    than imported)."""
    dt = np.dtype(dtype)
    if op == "sum":
        return dt.type(0)
    if op == "prod":
        return dt.type(1)
    if op in ("max", "min"):
        if dt.kind == "f":
            return dt.type(-np.inf if op == "max" else np.inf)
        info = np.iinfo(dt)
        return dt.type(info.min if op == "max" else info.max)
    raise ValueError(f"no identity for combine op {op!r}")


class PresentGroups:
    """A ``(present_codes, values)`` pair: one grouped-reduction layer whose
    trailing axis covers only the groups actually present, not the label
    universe — the host-boundary form of the sort engine's intermediates
    (docs/implementation.md "High-cardinality engine").

    ``present``: sorted unique dense codes, shape ``(n_present,)``.
    ``values``: ``(..., cap)`` with ``cap >= n_present``; column ``j < n_present``
    belongs to dense group ``present[j]``. When ``cap > n_present`` the
    first pad column carries the pipeline's empty-group value, which
    :meth:`scatter_dense` uses as the dense fill — that is what makes the
    expansion bit-identical to a dense run for every aggregation family.
    ``size``: the dense label universe the codes index into.
    """

    __slots__ = ("present", "values", "size")

    def __init__(self, present, values, size: int) -> None:
        self.present = np.asarray(present).reshape(-1)
        self.values = values
        self.size = int(size)
        if np.asarray(values).shape[-1] < len(self.present):
            raise ValueError(
                f"values trailing axis {np.asarray(values).shape[-1]} cannot "
                f"hold {len(self.present)} present groups"
            )

    @property
    def n_present(self) -> int:
        return int(self.present.shape[0])

    def __repr__(self) -> str:
        return (
            f"PresentGroups(n_present={self.n_present}, size={self.size}, "
            f"values={np.asarray(self.values).shape})"
        )

    def scatter_dense(self):
        """Expand to the dense ``(..., size)`` layout, host-side — absent
        groups take the first pad column's (empty-group) value."""
        res = np.asarray(self.values)
        npres = self.n_present
        if npres >= self.size:
            return np.ascontiguousarray(res[..., : self.size])
        if res.shape[-1] <= npres:
            raise ValueError(
                "scatter_dense needs >= 1 pad column when groups are absent "
                f"(cap {res.shape[-1]}, n_present {npres})"
            )
        fill = res[..., npres : npres + 1]
        out = np.empty(res.shape[:-1] + (self.size,), dtype=res.dtype)
        out[...] = fill
        out[..., self.present] = res[..., :npres]
        return out

    def merge(self, other: "PresentGroups", combine: str) -> "PresentGroups":
        """Union-merge two present-group INTERMEDIATES under one combine op
        ("sum" | "prod" | "max" | "min"): groups absent from one side
        contribute the op's identity, and the union table is re-banded with
        a pad column carrying the identity (an empty group's intermediate
        value), so the merged layer scatters like any other.

        No shipped runtime calls this yet — every current flow compacts
        against ONE host-known present table up front, so its carries never
        disagree. It exists (tested) as the building block for stores whose
        present sets grow between ingests — the incremental-aggregation
        direction of ROADMAP item 1, where two checkpointed compact layers
        with different tables must fold.

        Finalized values (a mean, a variance) do not merge — merge the
        underlying intermediate layers and finalize once, as every runtime
        here does.
        """
        if self.size != other.size:
            raise ValueError(f"universe mismatch: {self.size} != {other.size}")
        union = np.union1d(self.present, other.present)
        n_u = len(union)
        a = np.asarray(self.values)
        b = np.asarray(other.values)
        dtype = np.result_type(a.dtype, b.dtype)
        ident = _combine_identity(combine, dtype)
        cap = n_u + 1 if n_u < self.size else n_u
        lead = np.broadcast_shapes(a.shape[:-1], b.shape[:-1])
        out = np.full(lead + (cap,), ident, dtype=dtype)
        ia = np.searchsorted(union, self.present)
        ib = np.searchsorted(union, other.present)
        out[..., ia] = a[..., : self.n_present]
        bb = np.broadcast_to(b[..., : other.n_present], lead + (other.n_present,))
        sel = out[..., ib]
        if combine == "sum":
            out[..., ib] = sel + bb
        elif combine == "prod":
            out[..., ib] = sel * bb
        elif combine == "max":
            out[..., ib] = np.maximum(sel, bb)
        elif combine == "min":
            out[..., ib] = np.minimum(sel, bb)
        else:
            raise ValueError(f"unsupported combine op {combine!r}")
        return PresentGroups(union, out, self.size)


def merge_present_var(a, b):
    """Chan-merge two var-triple layers on the union of their present sets.

    ``a`` and ``b`` are ``(m2, total, count)`` triples of
    :class:`PresentGroups` — each side's three leaves share ONE present
    table (they came out of one ``var_chunk``). Groups absent from a side
    contribute the empty triple ``(0, 0, 0)``, which is exactly the Chan
    identity (``streaming._pair_merge``'s var branch with ``na == 0``
    reduces to the other side), so the union merge is the numpy restatement
    of the mesh/streaming var combine on a sparse domain — the var-family
    counterpart of :meth:`PresentGroups.merge`, built for stores whose
    present sets grow between ingests.
    """
    m2a, ta, na = a
    m2b, tb, nb = b
    if ta.size != tb.size:
        raise ValueError(f"universe mismatch: {ta.size} != {tb.size}")
    union = np.union1d(ta.present, tb.present)
    n_u = len(union)
    cap = n_u + 1 if n_u < ta.size else n_u
    ft = np.result_type(np.asarray(m2a.values).dtype, np.asarray(m2b.values).dtype)

    def _expand(pg: PresentGroups, dtype):
        v = np.asarray(pg.values)
        out = np.zeros(v.shape[:-1] + (cap,), dtype=dtype)
        out[..., np.searchsorted(union, pg.present)] = v[..., : pg.n_present]
        return out

    em2a, eta, ena = (_expand(x, ft) for x in (m2a, ta, na))
    em2b, etb, enb = (_expand(x, ft) for x in (m2b, tb, nb))
    nab = ena + enb
    tab = eta + etb
    with np.errstate(invalid="ignore", divide="ignore"):
        mua = eta / np.where(ena > 0, ena, 1)
        mub = etb / np.where(enb > 0, enb, 1)
        muab = tab / np.where(nab > 0, nab, 1)
        m2 = em2a + em2b + ena * (mua - muab) ** 2 + enb * (mub - muab) ** 2
    return tuple(PresentGroups(union, arr, ta.size) for arr in (m2, tab, nab))
