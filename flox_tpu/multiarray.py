"""MultiArray: a tuple-of-arrays that flows through the reduction machinery
as one value (parity: /root/reference/flox/multiarray.py:9-97, used by the
single-pass variance path, aggregations.py:348-451).

TPU-native twist: registered as a JAX pytree, so a MultiArray intermediate
(the variance triple ``(sum_sq_dev, sum, count)``) passes transparently
through ``jit`` / ``shard_map`` and collectives apply leaf-wise.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

try:  # register as pytree when jax is importable
    import jax.tree_util as _jtu
except ImportError:  # pragma: no cover
    _jtu = None


class MultiArray:
    __slots__ = ("arrays",)

    def __init__(self, arrays) -> None:
        self.arrays = tuple(arrays)

    def __len__(self) -> int:
        return len(self.arrays)

    def __iter__(self):
        return iter(self.arrays)

    def __getitem__(self, i):
        return self.arrays[i]

    @property
    def shape(self):
        return self.arrays[0].shape

    @property
    def ndim(self):
        return self.arrays[0].ndim

    @property
    def dtype(self):
        return self.arrays[0].dtype

    def astype(self, dtype, **kwargs) -> "MultiArray":
        return MultiArray(tuple(a.astype(dtype, **kwargs) for a in self.arrays))

    def reshape(self, *shape) -> "MultiArray":
        return MultiArray(tuple(a.reshape(*shape) for a in self.arrays))

    def squeeze(self, axis=None) -> "MultiArray":
        return MultiArray(tuple(a.squeeze(axis) for a in self.arrays))

    def map(self, fn: Callable[[Any], Any]) -> "MultiArray":
        return MultiArray(tuple(fn(a) for a in self.arrays))

    def __repr__(self) -> str:
        return f"MultiArray({self.arrays!r})"


def concatenate(arrays, axis=0):
    """Concatenate supporting MultiArray leaves (multiarray.py:60-71 parity)."""
    first = arrays[0]
    if isinstance(first, MultiArray):
        return MultiArray(
            tuple(np.concatenate([a.arrays[i] for a in arrays], axis=axis) for i in range(len(first)))
        )
    return np.concatenate(arrays, axis=axis)


if _jtu is not None:
    _jtu.register_pytree_node(
        MultiArray,
        lambda ma: (ma.arrays, None),
        lambda _, children: MultiArray(children),
    )
