"""Fleet federation: one merged view of N serving replicas.

A single replica already exposes ``/metrics`` + ``/debug/costs`` +
``/readyz`` (PR 8/9) — but ROADMAP item 2's router-plus-replicas topology
is undebuggable replica by replica: "is the FLEET saturated", "which
program is eating the fleet's device time", "which replicas left rotation"
all need the merged answer. This module is that aggregator, stdlib-only
like the exposition layer it scrapes:

* :class:`Federator` — scrapes every configured replica's
  ``/metrics?exemplars=1`` + ``/debug/costs`` + ``/readyz`` on an interval
  (``OPTIONS["fleet_scrape_interval"]``) and serves the merged view from
  one endpoint: counters and gauges summed across replicas (with the
  per-replica series preserved under ``replica="<name>"`` labels),
  histograms bucket-summed over the shared edges (exemplars max-merged per
  bucket; mismatched edge sets are a loud per-metric merge error, never a
  silent mis-merge — :func:`merge_histograms`), cost ledgers unioned
  (:func:`merge_cost_rows`), and a per-replica readiness table.
* ``python -m flox_tpu.fleet federate`` — the aggregator as a process:
  ``/metrics`` (merged text format), ``/debug/costs`` (merged ledger JSON,
  same shape the costs CLI reads), ``/replicas`` (readiness/status table),
  ``/alerts`` (fleet-deduped SLO alert rows, each tagged with its
  replica), ``/slo`` (per-replica SLO health + the deduped alerts),
  ``/healthz``, ``/readyz`` (200 while at least one replica is ready —
  what a front-door load balancer should probe).
* ``python -m flox_tpu.fleet top`` — the live ops console: a refresh loop
  over the same scrapes showing per-replica qps, p50/p99 request latency,
  queue depth, open breakers, HBM, resident datasets/stores, store
  freshness, the ALERTS column (``2F/1P`` = 2 firing / 1 pending), and
  the fleet's top cost rows. ``--once`` renders a single frame (scripts,
  tests); ``--plain`` skips the screen-clear escape.

The scrape also soft-GETs each replica's ``/debug/datasets`` +
``/debug/stores`` + ``/slo``: resident-state tables federate per NAME
(bytes summed, store generations and the freshest staleness kept per
replica), and alert rows dedup by (objective, window, replica) with the
most-live state winning — replicas without those planes contribute empty
tables instead of failing the round.

Replica targets are ``name=http://host:port`` pairs (bare URLs get a
``host:port`` name), from ``--replicas`` or ``OPTIONS["fleet_replicas"]``
(env ``FLOX_TPU_FLEET_REPLICAS``). A replica that labels its own series
(``FLOX_TPU_REPLICA_ID``) keeps its self-reported identity; unlabeled
replicas are attributed to their scrape-config name, so an operator can
federate a fleet that forgot to name itself.

All state lives on the :class:`Federator` instance — the module holds no
process-wide registries (nothing for ``cache.clear_all`` to reset).
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from .metric_names import (
    HBM_BYTES_IN_USE,
    HBM_BYTES_LIMIT,
    SERVE_BREAKERS_OPEN,
    SERVE_QUEUE_DEPTH,
    SERVE_REQUEST_MS,
    SERVE_REQUESTS,
    prom_name,
)

#: the scrape-side spellings of the series the top view reads, derived —
#: never respelled — from the shared registry names, so the fleet column
#: and the replica's exposition renderer cannot drift (FLX018 checks the
#: registry names against the contract's emit table)
_PROM_REQUESTS_TOTAL = prom_name(SERVE_REQUESTS, counter=True)
_PROM_REQUEST_MS = prom_name(SERVE_REQUEST_MS)
_PROM_QUEUE_DEPTH = prom_name(SERVE_QUEUE_DEPTH)
_PROM_BREAKERS_OPEN = prom_name(SERVE_BREAKERS_OPEN)
_PROM_HBM_IN_USE = prom_name(HBM_BYTES_IN_USE)
_PROM_HBM_LIMIT = prom_name(HBM_BYTES_LIMIT)

__all__ = [
    "Federator",
    "FleetMergeError",
    "ReplicaSnapshot",
    "federate",
    "merge_cost_rows",
    "merge_histograms",
    "parse_replica_targets",
    "parse_metrics_text",
    "render_prometheus",
    "render_top",
    "render_top_json",
]


class FleetMergeError(ValueError):
    """Two replicas' series for one metric cannot be merged — today that
    means mismatched histogram bucket edges (different builds, or a
    foreign exporter behind the scrape URL). Raised by
    :func:`merge_histograms` so the caller decides; the federator records
    it per metric and keeps the per-replica series instead of publishing a
    silently wrong sum."""


# ---------------------------------------------------------------------------
# scrape-side parsing
# ---------------------------------------------------------------------------

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_ESCAPE_RE = re.compile(r"\\(.)")
_IDENTITY_LABELS = ("replica", "host")


def _unescape(value: str) -> str:
    # single-pass: chained str.replace would decode the escaped literal
    # backslash-n (\\n) as backslash+newline instead of the original two
    # characters — each \x sequence must be resolved exactly once
    return _ESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), value
    )


def _parse_labels(text: str) -> dict[str, str]:
    return {k: _unescape(v) for k, v in _LABEL_RE.findall(text)}


def _labels_key(labels: dict[str, str]) -> tuple:
    """Canonical series identity: sorted label pairs, with the fleet
    identity labels (``replica``/``host``) and the histogram ``le`` edge
    stripped — identity is tracked per snapshot, edges per histogram."""
    return tuple(
        sorted(
            (k, v)
            for k, v in labels.items()
            if k not in _IDENTITY_LABELS and k != "le"
        )
    )


def parse_metrics_text(text: str) -> dict[str, Any]:
    """Parse the exposition layer's Prometheus text format back into
    mergeable structures.

    Returns ``{"counters": {(metric, labels): value}, "gauges": {...},
    "histograms": {(metric, labels): hist}, "replica": <self-reported
    label or None>}`` where ``hist`` carries the bucket ``edges`` (the
    ``le`` values in file order, ``+Inf`` excluded), the de-cumulated
    per-bucket ``counts``, ``sum``/``count``, and per-bucket ``exemplars``
    (``{bucket_index: [trace_id, value]}``). Malformed sample lines raise
    ``ValueError`` — a federator must know it is scraping garbage."""
    types: dict[str, str] = {}
    counters: dict[tuple, float] = {}
    gauges: dict[tuple, float] = {}
    hists: dict[tuple, dict] = {}
    replica: str | None = None
    #: distinct replica-label values seen (None = unlabeled series). A
    #: single replica's scrape has exactly one; more than one means the
    #: target is itself a federator (its output carries per-replica AND
    #: aggregate series) or a foreign exporter — folding those would
    #: silently double-count, so parsing rejects loudly instead.
    replicas_seen: set[str | None] = set()
    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        sample, _, exemplar = line.partition(" # ")
        name_part, _, value_part = sample.rpartition(" ")
        if not name_part:
            raise ValueError(f"metrics line {lineno}: unparseable sample {line!r}")
        value = float(value_part)
        metric, brace, label_text = name_part.partition("{")
        if brace and not label_text.endswith("}"):
            raise ValueError(f"metrics line {lineno}: unclosed label set {line!r}")
        labels = _parse_labels(label_text[:-1]) if brace else {}
        if not metric.startswith("flox_tpu_fleet_"):
            replicas_seen.add(labels.get("replica"))
            if len(replicas_seen) > 1:
                raise ValueError(
                    f"metrics line {lineno}: scrape carries more than one "
                    f"replica identity ({sorted(str(r) for r in replicas_seen)}) "
                    "— federate replicas, not another federator's merged view"
                )
        if replica is None and "replica" in labels:
            replica = labels["replica"]
        key = (metric, _labels_key(labels))
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if metric.endswith(suffix) and types.get(metric[: -len(suffix)]) == "histogram":
                base = metric[: -len(suffix)]
                break
        if base is not None:
            hist = hists.setdefault(
                (base, _labels_key(labels)),
                {"edges": [], "cum": [], "sum": 0.0, "count": 0, "exemplars": {}},
            )
            if metric.endswith("_bucket"):
                edge = labels.get("le")
                if edge is None:
                    raise ValueError(f"metrics line {lineno}: bucket without le")
                if edge != "+Inf":
                    if exemplar:
                        ex_labels = _parse_labels(exemplar)
                        _, _, ex_value = exemplar.rpartition(" ")
                        trace = ex_labels.get("trace_id")
                        if trace is not None:
                            hist["exemplars"][len(hist["edges"])] = [
                                trace, float(ex_value),
                            ]
                    hist["edges"].append(float(edge))
                    hist["cum"].append(value)
            elif metric.endswith("_sum"):
                hist["sum"] = value
            else:
                hist["count"] = int(value)
        elif types.get(metric, "").startswith("counter") or metric.endswith("_total"):
            counters[key] = counters.get(key, 0.0) + value
        else:
            gauges[key] = gauges.get(key, 0.0) + value
    for hist in hists.values():
        cum = hist.pop("cum")
        hist["counts"] = [
            c - (cum[i - 1] if i else 0.0) for i, c in enumerate(cum)
        ]
    return {
        "counters": counters,
        "gauges": gauges,
        "histograms": hists,
        "replica": replica,
    }


# ---------------------------------------------------------------------------
# merge math
# ---------------------------------------------------------------------------


def merge_histograms(a: dict, b: dict) -> dict:
    """Merge two parsed histograms sharing one edge set: bucket counts,
    total count, and sum add; exemplars max-merge per bucket (the fleet's
    worst observation in that bucket names its trace). Mismatched edges
    raise :class:`FleetMergeError` — summing unlike buckets would
    fabricate a distribution nobody observed."""
    if list(a["edges"]) != list(b["edges"]):
        raise FleetMergeError(
            f"histogram bucket edges differ ({len(a['edges'])} vs "
            f"{len(b['edges'])} edges, first mismatch at index "
            f"{next((i for i, (x, y) in enumerate(zip(a['edges'], b['edges'])) if x != y), min(len(a['edges']), len(b['edges'])))}) "
            "— refusing to merge unlike buckets"
        )
    exemplars = {int(k): list(v) for k, v in a.get("exemplars", {}).items()}
    for bucket, slot in (b.get("exemplars") or {}).items():
        bucket = int(bucket)
        held = exemplars.get(bucket)
        if held is None or slot[1] >= held[1]:
            exemplars[bucket] = list(slot)
    return {
        "edges": list(a["edges"]),
        "counts": [x + y for x, y in zip(a["counts"], b["counts"])],
        "sum": a["sum"] + b["sum"],
        "count": a["count"] + b["count"],
        "exemplars": exemplars,
    }


def merge_cost_rows(a: dict, b: dict) -> dict:
    """Union two cost-ledger rows for the same key: additive columns add,
    the max columns take the max — and ``last_slow_trace`` follows
    whichever row holds the fleet-wide worst dispatch."""
    out = {
        "dispatches": int(a.get("dispatches", 0)) + int(b.get("dispatches", 0)),
        "device_ms": float(a.get("device_ms", 0.0)) + float(b.get("device_ms", 0.0)),
        "bytes": int(a.get("bytes", 0)) + int(b.get("bytes", 0)),
        "compiles": int(a.get("compiles", 0)) + int(b.get("compiles", 0)),
        "compile_ms": float(a.get("compile_ms", 0.0)) + float(b.get("compile_ms", 0.0)),
        "hbm_peak": max(float(a.get("hbm_peak", 0.0)), float(b.get("hbm_peak", 0.0))),
    }
    wa, wb = float(a.get("device_ms_max", 0.0)), float(b.get("device_ms_max", 0.0))
    worst = a if wa >= wb else b
    out["device_ms_max"] = max(wa, wb)
    out["last_slow_trace"] = worst.get("last_slow_trace")
    return out


def _hist_percentile(hist: dict, q: float) -> float:
    """Interpolated percentile over a parsed/merged histogram (same walk
    as ``telemetry._hist_percentile``, minus the observed min/max clamp —
    scraped histograms don't carry them)."""
    count = hist.get("count") or 0
    if not count:
        return 0.0
    target = max(0.0, min(1.0, q)) * count
    cum = 0.0
    for i, c in enumerate(hist["counts"]):
        if not c:
            continue
        if cum + c >= target:
            lo = hist["edges"][i - 1] if i else 0.0
            hi = hist["edges"][i]
            return lo + ((target - cum) / c) * (hi - lo)
        cum += c
    return hist["edges"][-1] if hist["edges"] else 0.0


# ---------------------------------------------------------------------------
# replica snapshots + the federated view
# ---------------------------------------------------------------------------


@dataclass
class ReplicaSnapshot:
    """One scrape round's result for one replica."""

    name: str
    url: str
    ok: bool = False
    error: str | None = None
    ready: bool | None = None
    ready_reason: str = ""
    metrics: dict = field(default_factory=dict)
    costs: dict = field(default_factory=dict)
    programs: dict = field(default_factory=dict)
    datasets: dict = field(default_factory=dict)
    stores: dict = field(default_factory=dict)
    slo: dict = field(default_factory=dict)
    alerts: list = field(default_factory=list)
    scraped_at: float = 0.0

    @property
    def replica_label(self) -> str:
        """The identity the merged view attributes this replica's series
        to: its self-reported ``replica`` label when it set one, else the
        scrape-config name."""
        return (self.metrics or {}).get("replica") or self.name


def _http_get(url: str, timeout: float) -> tuple[int, str]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode(errors="replace")


def scrape_replica(name: str, url: str, timeout: float = 5.0) -> ReplicaSnapshot:
    """One replica's ``/metrics?exemplars=1`` + ``/debug/costs`` +
    ``/readyz``, parsed. Network/parse failures mark the snapshot
    ``ok=False`` with the error — an unreachable replica is a ROW in the
    fleet view, never an aggregator crash."""
    snap = ReplicaSnapshot(name=name, url=url.rstrip("/"), scraped_at=time.time())
    try:
        status, body = _http_get(f"{snap.url}/metrics?exemplars=1", timeout)
        if status != 200:
            raise ValueError(f"/metrics answered {status}")
        snap.metrics = parse_metrics_text(body)
        status, body = _http_get(f"{snap.url}/debug/costs", timeout)
        if status == 200:
            snap.costs = json.loads(body)
        # compiled-program cards (costmodel plane): absent on replicas
        # running with the plane off — an empty table, never a scrape fail
        status, body = _http_get(f"{snap.url}/debug/programs", timeout)
        if status == 200:
            snap.programs = json.loads(body).get("programs") or {}
        # resident state (dataset registry + durable stores) and the SLO /
        # alert plane: soft scrapes — older replicas (or replicas with the
        # planes dark) simply contribute empty tables, never a scrape fail
        status, body = _http_get(f"{snap.url}/debug/datasets", timeout)
        if status == 200:
            snap.datasets = json.loads(body)
        status, body = _http_get(f"{snap.url}/debug/stores", timeout)
        if status == 200:
            snap.stores = json.loads(body)
        status, body = _http_get(f"{snap.url}/slo", timeout)
        if status == 200:
            snap.slo = json.loads(body)
            snap.alerts = list(snap.slo.get("alerts") or [])
        status, body = _http_get(f"{snap.url}/readyz", timeout)
        snap.ready = status == 200
        snap.ready_reason = body.strip()
        snap.ok = True
    except Exception as exc:  # noqa: BLE001 — one dead replica must not kill the view
        snap.error = f"{type(exc).__name__}: {exc}"
        snap.ok = False
    return snap


def federate(snapshots: list[ReplicaSnapshot]) -> dict[str, Any]:
    """Merge N replica snapshots into one fleet view.

    Counters/gauges: per-replica series preserved (keyed by replica
    label) plus the fleet sum. Histograms: bucket-summed over shared
    edges; a :class:`FleetMergeError` removes that metric's merged series
    and records the error under ``merge_errors`` (the per-replica series
    survive). Cost ledgers: unioned via :func:`merge_cost_rows` with a
    ``by_replica`` breakdown. Readiness: one row per replica."""
    view: dict[str, Any] = {
        "counters": {},     # (metric, labels) -> {"replicas": {name: v}, "total": v}
        "gauges": {},
        "histograms": {},   # (metric, labels) -> {"replicas": {...}, "merged": hist|None}
        "merge_errors": {},  # metric -> error text
        "cost_by_program": {},
        "cost_by_tenant": {},
        "cost_by_replica": {},
        "programs": {},     # card digest -> {card fields, labels, observed merged}
        "datasets": {},     # name -> {"bytes", "pins", "hits", "replicas": {...}}
        "stores": {},       # name -> {"state_bytes", "generations", "staleness_s", ...}
        "alerts": [],       # deduped alert rows, each tagged with its replica
        "slo": {},          # replica label -> that replica's /slo health summary
        "replicas": [],
    }
    #: (objective, window, replica) -> alert row — the dedup table behind
    #: view["alerts"]; a replica re-reporting one alert keeps the
    #: most-severe / most-live row (firing beats pending beats resolved)
    alert_table: dict[tuple, dict] = {}
    state_rank = {"firing": 0, "pending": 1, "resolved": 2}
    severity_rank = {"page": 0, "ticket": 1}
    for snap in snapshots:
        label = snap.replica_label
        view["replicas"].append(
            {
                "name": snap.name,
                "replica": label,
                "url": snap.url,
                "ok": snap.ok,
                "ready": snap.ready,
                "reason": snap.ready_reason,
                "error": snap.error,
                "scraped_at": snap.scraped_at,
                "host": (snap.costs or {}).get("host"),
            }
        )
        if not snap.ok:
            continue
        for kind in ("counters", "gauges"):
            for key, value in snap.metrics.get(kind, {}).items():
                slot = view[kind].setdefault(key, {"replicas": {}, "total": 0.0})
                slot["replicas"][label] = slot["replicas"].get(label, 0.0) + value
                slot["total"] += value
        for key, hist in snap.metrics.get("histograms", {}).items():
            slot = view["histograms"].setdefault(key, {"replicas": {}, "merged": None})
            slot["replicas"][label] = hist
            if key[0] in view["merge_errors"]:
                continue
            try:
                slot["merged"] = (
                    dict(hist, exemplars=dict(hist.get("exemplars") or {}))
                    if slot["merged"] is None
                    else merge_histograms(slot["merged"], hist)
                )
            except FleetMergeError as exc:
                view["merge_errors"][key[0]] = str(exc)
                slot["merged"] = None
        for axis in ("cost_by_program", "cost_by_tenant"):
            for row_key, row in (snap.costs.get(axis) or {}).items():
                held = view[axis].get(row_key)
                view[axis][row_key] = (
                    dict(row) if held is None else merge_cost_rows(held, row)
                )
                view["cost_by_replica"].setdefault(axis, {}).setdefault(
                    row_key, {}
                )[label] = dict(row)
        for prog_label, row in (snap.programs or {}).items():
            _merge_program_row(view["programs"], prog_label, row)
        for row in (snap.datasets or {}).get("datasets") or []:
            name = str(row.get("name"))
            slot = view["datasets"].setdefault(
                name, {"bytes": 0, "pins": 0, "hits": 0, "replicas": {}}
            )
            slot["bytes"] += int(row.get("nbytes", 0))
            slot["pins"] += int(row.get("pins", 0))
            slot["hits"] += int(row.get("hits", 0))
            slot["replicas"][label] = dict(row)
        for row in (snap.stores or {}).get("stores") or []:
            name = str(row.get("store"))
            slot = view["stores"].setdefault(
                name,
                {"state_bytes": 0, "generations": {}, "staleness_s": None, "replicas": {}},
            )
            slot["state_bytes"] += int(row.get("nbytes", 0))
            if row.get("gen") is not None:
                slot["generations"][label] = int(row["gen"])
            stale = row.get("staleness_s")
            if stale is not None:
                # the FRESHEST copy wins: one replica still ingesting means
                # the fleet's view of the store is that fresh
                held = slot["staleness_s"]
                slot["staleness_s"] = (
                    float(stale) if held is None else min(held, float(stale))
                )
            slot["replicas"][label] = dict(row)
        if snap.slo:
            view["slo"][label] = {
                "healthy": bool(snap.slo.get("healthy", True)),
                "evaluated_at": snap.slo.get("evaluated_at"),
                "objectives": [
                    {
                        "name": o.get("name"),
                        "kind": o.get("kind"),
                        "healthy": o.get("healthy"),
                        "budget_remaining": o.get("budget_remaining"),
                    }
                    for o in snap.slo.get("objectives") or []
                ],
            }
        for alert in snap.alerts or []:
            key = (alert.get("objective"), alert.get("window"), label)
            row = dict(alert, replica=label)
            held = alert_table.get(key)
            if held is None or (
                state_rank.get(row.get("state"), 9),
                severity_rank.get(row.get("severity"), 9),
            ) < (
                state_rank.get(held.get("state"), 9),
                severity_rank.get(held.get("severity"), 9),
            ):
                alert_table[key] = row
    view["alerts"] = sorted(
        alert_table.values(),
        key=lambda a: (
            state_rank.get(a.get("state"), 9),
            severity_rank.get(a.get("severity"), 9),
            str(a.get("objective")),
            str(a.get("replica")),
        ),
    )
    # a merge error poisons EVERY label set of its metric: sibling keys
    # processed before the error still hold a partial (first-replicas-only)
    # merged histogram, and publishing that as the fleet aggregate would be
    # exactly the silent mis-merge the error exists to prevent
    for (metric, _labels), slot in view["histograms"].items():
        if metric in view["merge_errors"]:
            slot["merged"] = None
    return view


def _merge_program_row(table: dict, label: str, row: dict) -> None:
    """Union one replica's compiled-program card row into the fleet view.

    Cards union by DIGEST (the (label, input signature) identity — two
    replicas serving the same program hold byte-identical analytical
    numbers, so the card fields come from whichever scraped first), labels
    accumulate, and the observed ledger rows merge exactly like cost rows.
    Utilization and drift recompute from the merged totals: utilization is
    model-time / observed-time, so ``predicted_ms x dispatches /
    device_ms`` holds across replicas."""
    digest = str(row.get("digest") or f"?{label}")
    held = table.get(digest)
    if held is None:
        # card fields only: the observed-JOIN fields (utilization,
        # achieved_*, drift) are per-replica numbers and must be
        # recomputed from the merged totals below, never copied from
        # whichever replica scraped first
        held = table[digest] = {
            k: v
            for k, v in row.items()
            if k
            not in (
                "observed", "label", "utilization", "achieved_gbps",
                "achieved_gflops", "observed_ms_per_dispatch", "drift_ratio",
            )
        }
        held["digest"] = digest  # present even for rows scraped without one
        held["labels"] = []
        held["observed"] = None
    if label not in held["labels"]:
        held["labels"].append(label)
    observed = row.get("observed")
    if observed:
        held["observed"] = (
            dict(observed)
            if held["observed"] is None
            else merge_cost_rows(held["observed"], observed)
        )
        merged = held["observed"]
        dispatches = int(merged.get("dispatches", 0))
        # compile-net, mirroring costmodel._net_device_ms: the merged row
        # carries the fleet's compile wall too, and cold replicas must not
        # read as fleet-wide drift
        device_ms = max(
            0.0,
            float(merged.get("device_ms", 0.0)) - float(merged.get("compile_ms", 0.0)),
        )
        predicted = float(held.get("predicted_ms") or 0.0)
        if dispatches > 0 and device_ms > 0:
            held["utilization"] = round(predicted * dispatches / device_ms, 6)
            held["observed_ms_per_dispatch"] = round(device_ms / dispatches, 6)
            seconds = device_ms / 1e3
            held["achieved_gbps"] = round(
                float(held.get("bytes_accessed") or 0.0) * dispatches / seconds / 1e9, 6
            )
            held["achieved_gflops"] = round(
                float(held.get("flops") or 0.0) * dispatches / seconds / 1e9, 6
            )
            model_ms = row.get("model_ms")
            if model_ms:
                held["model_ms"] = float(model_ms)
                held["drift_ratio"] = round(
                    (device_ms / dispatches) / float(model_ms), 6
                )


# ---------------------------------------------------------------------------
# rendering: merged /metrics text + the ops-console frame
# ---------------------------------------------------------------------------


def _esc(value: str) -> str:
    """Label-value escaping — the exposition layer's, single-sourced: the
    federated output must round-trip byte-identically with what the
    replicas emit."""
    from .exposition import _escape_label

    return _escape_label(value)


def _series(metric: str, labels: tuple, extra: str = "") -> str:
    pairs = [f'{k}="{_esc(v)}"' for k, v in labels]
    if extra:
        pairs.insert(0, extra)
    return f"{metric}{{{','.join(pairs)}}}" if pairs else metric


def _fmt_value(value: float) -> str:
    """Sample-value formatting — the exposition layer's (see :func:`_esc`)."""
    from .exposition import _fmt

    return _fmt(value)


def render_prometheus(view: dict[str, Any], exemplars: bool = False) -> str:
    """The federated view in the same text format the replicas speak.

    Every scraped series appears twice: once per replica under its
    ``replica="<label>"`` label, and once WITHOUT a replica label as the
    fleet aggregate (counters/gauges summed, histograms bucket-summed) —
    so both "sum by replica" dashboards and plain fleet-total queries read
    straight off one scrape. Fleet-level health (replica counts, scrape
    errors, per-metric merge errors) rides ``flox_tpu_fleet_*``."""
    lines: list[str] = []
    typed: set[str] = set()

    def _type_line(metric: str, kind: str) -> None:
        # one TYPE line per metric NAME, however many label sets — a
        # second one makes a spec-compliant scraper drop the whole scrape
        if metric not in typed:
            typed.add(metric)
            lines.append(f"# TYPE {metric} {kind}")

    replicas = view.get("replicas", [])
    lines.append("# TYPE flox_tpu_fleet_replicas gauge")
    lines.append(f"flox_tpu_fleet_replicas {len(replicas)}")
    lines.append("# TYPE flox_tpu_fleet_replicas_ready gauge")
    lines.append(
        f"flox_tpu_fleet_replicas_ready {sum(1 for r in replicas if r.get('ready'))}"
    )
    lines.append("# TYPE flox_tpu_fleet_scrape_errors gauge")
    lines.append(
        f"flox_tpu_fleet_scrape_errors {sum(1 for r in replicas if not r.get('ok'))}"
    )
    if view.get("merge_errors"):
        lines.append("# TYPE flox_tpu_fleet_merge_errors gauge")
        for metric in sorted(view["merge_errors"]):
            lines.append(
                f'flox_tpu_fleet_merge_errors{{metric="{_esc(metric)}"}} 1'
            )
    for kind, prom_type in (("counters", "counter"), ("gauges", "gauge")):
        for (metric, labels), slot in sorted(view.get(kind, {}).items()):
            _type_line(metric, prom_type)
            for replica in sorted(slot["replicas"]):
                extra = f'replica="{_esc(replica)}"'
                lines.append(
                    f"{_series(metric, labels, extra)} "
                    f"{_fmt_value(slot['replicas'][replica])}"
                )
            lines.append(f"{_series(metric, labels)} {_fmt_value(slot['total'])}")
    for (metric, labels), slot in sorted(view.get("histograms", {}).items()):
        _type_line(metric, "histogram")
        for replica in sorted(slot["replicas"]):
            hist = slot["replicas"][replica]
            extra = f'replica="{_esc(replica)}"'
            lines += _hist_lines(metric, labels, hist, extra, exemplars)
        if slot["merged"] is not None:
            lines += _hist_lines(metric, labels, slot["merged"], "", exemplars)
    return "\n".join(lines) + "\n"


def _hist_lines(
    metric: str, labels: tuple, hist: dict, extra: str, exemplars: bool
) -> list[str]:
    out = []
    cum = 0.0
    slots = (hist.get("exemplars") or {}) if exemplars else {}
    base_pairs = ([extra] if extra else []) + [
        f'{k}="{_esc(v)}"' for k, v in labels
    ]
    for i, (edge, n) in enumerate(zip(hist["edges"], hist["counts"])):
        cum += n
        label_pairs = base_pairs + [f'le="{_fmt_value(edge)}"']
        line = f"{metric}_bucket{{{','.join(label_pairs)}}} {_fmt_value(cum)}"
        slot = slots.get(i) or slots.get(str(i))
        if slot is not None:
            line += f' # {{trace_id="{_esc(slot[0])}"}} {_fmt_value(slot[1])}'
        out.append(line)
    label_pairs = list(base_pairs)
    inf_pairs = label_pairs + ['le="+Inf"']
    out.append(f"{metric}_bucket{{{','.join(inf_pairs)}}} {_fmt_value(hist['count'])}")
    suffix = f"{{{','.join(label_pairs)}}}" if label_pairs else ""
    out.append(f"{metric}_sum{suffix} {_fmt_value(hist['sum'])}")
    out.append(f"{metric}_count{suffix} {_fmt_value(hist['count'])}")
    return out


def render_top(
    view: dict[str, Any],
    prev: dict[str, Any] | None = None,
    interval: float = 0.0,
    top: int = 5,
    width: int = 120,
) -> str:
    """One ops-console frame: per-replica vitals (now including resident
    datasets/stores, store freshness, and the SLO alert column) + the
    fleet's top cost rows + any firing/pending alerts.
    ``prev``/``interval`` turn the monotonically increasing
    ``serve.requests`` counter into a qps column (blank on the first
    frame). This is the ANSI *formatting* of exactly the dict
    :func:`render_top_json` builds — the two views cannot drift."""
    frame = render_top_json(view, prev=prev, interval=interval, top=top)
    lines = [
        f"flox_tpu fleet — {len(frame['replicas'])} replica(s), "
        f"{time.strftime('%H:%M:%S')}",
        "",
        f"{'replica':<16} {'state':<12} {'qps':>7} {'p50 ms':>9} {'p99 ms':>9} "
        f"{'queue':>6} {'brk':>4} {'hbm':>10} {'ds':>4} {'st':>4} "
        f"{'fresh':>7} {'alerts':>6}  endpoint",
        "-" * width,
    ]
    for row in frame["replicas"]:
        qps = f"{row['qps']:.1f}" if row["qps"] is not None else ""
        p50 = f"{row['p50_ms']:.2f}" if row["p50_ms"] is not None else "-"
        p99 = f"{row['p99_ms']:.2f}" if row["p99_ms"] is not None else "-"
        hbm = row["hbm_bytes"]
        limit = row["hbm_bytes_limit"]
        hbm_s = f"{hbm / 2**30:.2f}GiB" if hbm else "-"
        if hbm and limit:
            # the bytes_limit gauge makes the column a fraction of capacity
            hbm_s = f"{hbm / 2**30:.2f}G/{100 * hbm / limit:.0f}%"
        stale = row["staleness_s"]
        fresh = f"{stale:.0f}s" if stale is not None else "-"
        firing, pending = row["alerts_firing"], row["alerts_pending"]
        alerts_s = "-" if not (firing or pending) else f"{firing}F/{pending}P"
        lines.append(
            f"{row['replica'][:16]:<16} {row['state'][:12]:<12} {qps:>7} "
            f"{p50:>9} {p99:>9} {row['queue_depth']:>6} "
            f"{row['breakers_open']:>4} {hbm_s:>10} {row['datasets']:>4} "
            f"{row['stores']:>4} {fresh:>7} {alerts_s:>6}  {row['url']}"
        )
    live_alerts = [
        a for a in frame["alerts"] if a.get("state") in ("firing", "pending")
    ]
    if live_alerts:
        lines += ["", "alerts (most severe first):"]
        for a in live_alerts:
            lines.append(
                f"  [{str(a.get('state', '?')).upper():<7}] "
                f"{a.get('objective')}/{a.get('window')} "
                f"severity={a.get('severity')} replica={a.get('replica')} "
                f"burn={a.get('burn_short', 0):.1f}x/{a.get('burn_long', 0):.1f}x"
            )
    lines += [
        "",
        f"top {top} cost rows (fleet-unioned /debug/costs, by device time):",
        f"{'program key':<46} {'disp':>6} {'device ms':>11} {'MBytes':>9} "
        f"{'util':>7}  slow trace",
        "-" * width,
    ]
    if not frame["top_costs"]:
        lines.append("  (no cost rows yet)")
    for row in frame["top_costs"]:
        util = row["utilization"]
        lines.append(
            f"{row['program'][:46]:<46} {row['dispatches']:>6} "
            f"{row['device_ms']:>11.2f} "
            f"{row['bytes'] / 1e6:>9.2f} "
            f"{('%.1f%%' % (100 * util)) if util is not None else '-':>7}  "
            f"{str(row['last_slow_trace'] or '-')[:24]}"
        )
    if frame["merge_errors"]:
        lines += ["", "merge errors (per-replica series kept, fleet sum withheld):"]
        for metric, err in sorted(frame["merge_errors"].items()):
            lines.append(f"  {metric}: {err[:width - 4]}")
    return "\n".join(lines)


def render_top_json(
    view: dict[str, Any],
    prev: dict[str, Any] | None = None,
    interval: float = 0.0,
    top: int = 5,
) -> dict[str, Any]:
    """The ops-console frame as a JSON-safe object (``fleet top --json``):
    the same per-replica vitals and fleet-unioned top cost rows the ANSI
    frame renders, shaped for alerting scripts instead of eyeballs. ``qps``
    is ``None`` on the first frame (no prior counter sample to diff)."""

    def counter(view_: dict, metric: str, replica: str) -> float:
        slot = view_.get("counters", {}).get((metric, ()))
        return float(slot["replicas"].get(replica, 0.0)) if slot else 0.0

    def gauge(metric: str, replica: str) -> float:
        slot = view.get("gauges", {}).get((metric, ()))
        return float(slot["replicas"].get(replica, 0.0)) if slot else 0.0

    replicas = []
    for row in view.get("replicas", []):
        label = row["replica"]
        if not row.get("ok"):
            state = "unreachable"
        elif row.get("ready"):
            state = "ready"
        else:
            state = row.get("reason") or "not-ready"
        qps = None
        if prev is not None and interval > 0:
            delta = counter(view, _PROM_REQUESTS_TOTAL, label) - counter(
                prev, _PROM_REQUESTS_TOTAL, label
            )
            qps = round(max(0.0, delta) / interval, 3)
        hist = (
            view.get("histograms", {})
            .get((_PROM_REQUEST_MS, ()), {})
            .get("replicas", {})
            .get(label)
        )
        limit = gauge(_PROM_HBM_LIMIT, label)
        ds_rows = [
            slot["replicas"][label]
            for slot in view.get("datasets", {}).values()
            if label in slot.get("replicas", {})
        ]
        st_rows = [
            slot["replicas"][label]
            for slot in view.get("stores", {}).values()
            if label in slot.get("replicas", {})
        ]
        stale = [
            float(r["staleness_s"]) for r in st_rows if r.get("staleness_s") is not None
        ]
        my_alerts = [
            a for a in view.get("alerts", []) if a.get("replica") == label
        ]
        replicas.append(
            {
                "replica": label,
                "url": row["url"],
                "state": state,
                "error": row.get("error"),
                "qps": qps,
                "p50_ms": round(_hist_percentile(hist, 0.50), 4) if hist else None,
                "p99_ms": round(_hist_percentile(hist, 0.99), 4) if hist else None,
                "queue_depth": int(gauge(_PROM_QUEUE_DEPTH, label)),
                "breakers_open": int(gauge(_PROM_BREAKERS_OPEN, label)),
                "hbm_bytes": gauge(_PROM_HBM_IN_USE, label),
                "hbm_bytes_limit": limit or None,
                "datasets": len(ds_rows),
                "dataset_bytes": sum(int(r.get("nbytes", 0)) for r in ds_rows),
                "stores": len(st_rows),
                # the STALEST store on this replica: the freshness headline
                "staleness_s": round(max(stale), 3) if stale else None,
                "alerts_firing": sum(
                    1 for a in my_alerts if a.get("state") == "firing"
                ),
                "alerts_pending": sum(
                    1 for a in my_alerts if a.get("state") == "pending"
                ),
                "slo_healthy": (
                    view.get("slo", {}).get(label, {}).get("healthy")
                    if label in view.get("slo", {})
                    else None
                ),
            }
        )
    util_by_label: dict[str, float] = {}
    programs = []
    for digest, prow in sorted(view.get("programs", {}).items()):
        for plabel in prow.get("labels", []):
            if prow.get("utilization") is not None:
                util_by_label[plabel] = float(prow["utilization"])
        programs.append(dict(prow))
    ranked = sorted(
        view.get("cost_by_program", {}).items(),
        key=lambda kv: (
            -float(kv[1].get("device_ms", 0.0)),
            -int(kv[1].get("dispatches", 0)),
        ),
    )[:top]
    top_costs = [
        {
            "program": label,
            "dispatches": int(row.get("dispatches", 0)),
            "device_ms": float(row.get("device_ms", 0.0)),
            "bytes": float(row.get("bytes", 0)),
            "utilization": util_by_label.get(label),
            "last_slow_trace": row.get("last_slow_trace"),
        }
        for label, row in ranked
    ]
    return {
        "ts": time.time(),
        "replicas": replicas,
        "top_costs": top_costs,
        "programs": programs,
        "alerts": [dict(a) for a in view.get("alerts", [])],
        "merge_errors": dict(view.get("merge_errors", {})),
    }


# ---------------------------------------------------------------------------
# the federator process
# ---------------------------------------------------------------------------


def parse_replica_targets(spec: str | None) -> list[tuple[str, str]]:
    """``"a=http://h:1,b=http://h:2"`` (or bare URLs) ->
    ``[(name, url), ...]``. Bare URLs are named ``host:port``."""
    if not spec:
        raise ValueError(
            "no replicas configured: pass --replicas name=url[,name=url...] "
            "or set FLOX_TPU_FLEET_REPLICAS"
        )
    out: list[tuple[str, str]] = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, url = part.partition("=")
        if not sep:
            url = part
            name = re.sub(r"^https?://", "", part).rstrip("/")
        if not url.startswith(("http://", "https://")):
            raise ValueError(f"replica target {part!r}: url must be http(s)://...")
        out.append((name, url))
    if not out:
        raise ValueError(f"no replica targets parsed from {spec!r}")
    return out


class Federator:
    """Scrape loop + merged-view cache + HTTP endpoint, one instance per
    aggregator process (no module-level state)."""

    def __init__(
        self,
        targets: list[tuple[str, str]],
        interval: float | None = None,
        timeout: float = 5.0,
    ) -> None:
        from .options import OPTIONS

        self.targets = list(targets)
        self.interval = float(
            interval if interval is not None else OPTIONS["fleet_scrape_interval"]
        )
        self.timeout = timeout
        self._lock = threading.Lock()
        self._view: dict[str, Any] = federate([])
        self._snapshots: list[ReplicaSnapshot] = []
        self._rounds = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._httpd: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self.port: int | None = None

    # -- scraping -----------------------------------------------------------

    def scrape_once(self) -> dict[str, Any]:
        """One scrape round; returns (and caches) the merged view.

        Targets are scraped CONCURRENTLY: sequentially, one black-holed
        replica would stall every round by its full timeout and a wide
        fleet could never meet the scrape interval — concurrent, a round
        costs ~one slowest-target round trip regardless of fleet size."""
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=min(16, max(1, len(self.targets))),
            thread_name_prefix="flox-tpu-fleet-scrape",
        ) as pool:
            snapshots = list(
                pool.map(
                    lambda t: scrape_replica(t[0], t[1], timeout=self.timeout),
                    self.targets,
                )
            )
        view = federate(snapshots)
        with self._lock:
            self._snapshots = snapshots
            self._view = view
            self._rounds += 1
        return view

    def view(self) -> dict[str, Any]:
        with self._lock:
            return self._view

    @property
    def rounds(self) -> int:
        with self._lock:
            return self._rounds

    def start(self) -> None:
        """Start the background scrape loop (daemon; the first round runs
        immediately so the endpoint never serves an empty view for a full
        interval)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def _run() -> None:
            while True:
                # not a retry loop: rounds are independent scrapes, and one
                # bad round (a replica mid-restart, a torn response) must
                # never kill federation — the error is kept for /replicas
                try:
                    self.scrape_once()
                except Exception as exc:  # noqa: FLX006
                    with self._lock:
                        self._view = dict(
                            self._view, scrape_loop_error=f"{type(exc).__name__}: {exc}"
                        )
                if self._stop.wait(self.interval):
                    return

        self._thread = threading.Thread(
            target=_run, name="flox-tpu-fleet-scraper", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.timeout + self.interval)
            self._thread = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._http_thread is not None:
            self._http_thread.join(timeout=5)
            self._http_thread = None

    # -- serving ------------------------------------------------------------

    def serve(self, port: int | None = None, host: str = "127.0.0.1") -> int:
        """Serve the merged view over HTTP (daemon thread); returns the
        bound port. ``port=None`` reads ``OPTIONS["fleet_port"]`` (0 there
        = ephemeral)."""
        from .options import OPTIONS

        if port is None:
            port = OPTIONS["fleet_port"]
        federator = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — http.server contract
                path, _, query = self.path.partition("?")
                view = federator.view()
                if path == "/metrics":
                    import urllib.parse as _p

                    with_ex = _p.parse_qs(query).get("exemplars", ["0"])[0] == "1"
                    body = render_prometheus(view, exemplars=with_ex).encode()
                    status, ctype = 200, "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/debug/costs":
                    payload = {
                        "cost_by_program": view["cost_by_program"],
                        "cost_by_tenant": view["cost_by_tenant"],
                        "cost_by_replica": view["cost_by_replica"],
                        "replica": "_fleet",
                    }
                    body = (json.dumps(payload, default=str) + "\n").encode()
                    status, ctype = 200, "application/json; charset=utf-8"
                elif path == "/replicas":
                    body = (json.dumps(view["replicas"], default=str) + "\n").encode()
                    status, ctype = 200, "application/json; charset=utf-8"
                elif path == "/alerts":
                    alerts = view.get("alerts", [])
                    payload = {
                        "alerts": alerts,
                        "firing": sum(
                            1 for a in alerts if a.get("state") == "firing"
                        ),
                        "healthy": not any(
                            a.get("state") == "firing" for a in alerts
                        ),
                        "replica": "_fleet",
                    }
                    body = (json.dumps(payload, default=str) + "\n").encode()
                    status, ctype = 200, "application/json; charset=utf-8"
                elif path == "/slo":
                    by_replica = view.get("slo", {})
                    payload = {
                        "healthy": all(
                            s.get("healthy", True) for s in by_replica.values()
                        )
                        and not any(
                            a.get("state") == "firing"
                            for a in view.get("alerts", [])
                        ),
                        "replicas": by_replica,
                        "alerts": view.get("alerts", []),
                        "replica": "_fleet",
                    }
                    body = (json.dumps(payload, default=str) + "\n").encode()
                    status, ctype = 200, "application/json; charset=utf-8"
                elif path == "/healthz":
                    body, status, ctype = b"ok\n", 200, "text/plain; charset=utf-8"
                elif path == "/readyz":
                    ready = any(r.get("ready") for r in view["replicas"])
                    body = b"ready\n" if ready else b"no-ready-replicas\n"
                    status = 200 if ready else 503
                    ctype = "text/plain; charset=utf-8"
                else:
                    body, status, ctype = b"not found\n", 404, "text/plain; charset=utf-8"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, format: str, *args: Any) -> None:
                pass  # scrape-rate probes must not spam stderr

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="flox-tpu-fleet-http", daemon=True
        )
        self._http_thread.start()
        return self.port


# ---------------------------------------------------------------------------
# CLI: python -m flox_tpu.fleet {federate,top}
# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    import argparse

    from .options import OPTIONS

    parser = argparse.ArgumentParser(
        prog="python -m flox_tpu.fleet",
        description="Fleet observability: metrics federation + live ops console.",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    federate_cmd = sub.add_parser(
        "federate",
        help="scrape N replicas and serve the merged /metrics + "
        "/debug/costs + /replicas + /alerts + /slo view from one endpoint",
    )
    top_cmd = sub.add_parser(
        "top", help="live per-replica vitals + fleet top-cost console"
    )
    for cmd in (federate_cmd, top_cmd):
        cmd.add_argument(
            "--replicas", default=None,
            help="comma-separated name=url targets (default: "
            "FLOX_TPU_FLEET_REPLICAS)",
        )
        cmd.add_argument(
            "--interval", type=float, default=None,
            help="seconds between scrape rounds (default: "
            "OPTIONS['fleet_scrape_interval'])",
        )
        cmd.add_argument("--timeout", type=float, default=5.0)
        cmd.add_argument(
            "--once", action="store_true",
            help="one scrape round, print the result, exit (scripts/tests)",
        )
    federate_cmd.add_argument(
        "--port", type=int, default=None,
        help="TCP port for the merged endpoint (default: "
        "OPTIONS['fleet_port']; 0 binds an ephemeral port and prints it)",
    )
    federate_cmd.add_argument("--host", default="127.0.0.1")
    top_cmd.add_argument(
        "--top", type=int, default=5, help="cost rows shown (default 5)"
    )
    top_cmd.add_argument(
        "--plain", action="store_true",
        help="never clear the screen between frames (logs, pipes)",
    )
    top_cmd.add_argument(
        "--json", action="store_true",
        help="emit one JSON document per frame instead of the ANSI console "
        "— alerting scripts consume per-replica state without scraping the "
        "frame (implies --plain)",
    )
    args = parser.parse_args(argv)
    try:
        targets = parse_replica_targets(args.replicas or OPTIONS["fleet_replicas"])
    except ValueError as exc:
        parser.error(str(exc))
    federator = Federator(targets, interval=args.interval, timeout=args.timeout)
    if args.command == "federate":
        view = federator.scrape_once()
        if args.once:
            print(render_prometheus(view), end="")
            return 0
        federator.start()
        port = federator.serve(port=args.port)
        print(
            f"federating {len(targets)} replica(s) every {federator.interval:g}s "
            f"on http://{args.host}:{port} (/metrics /debug/costs /replicas "
            f"/alerts /slo /healthz /readyz)",
            flush=True,
        )
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            federator.stop()
        return 0
    # top: the refresh-loop console
    prev: dict[str, Any] | None = None
    try:
        while True:
            t0 = time.time()
            view = federator.scrape_once()
            if args.json:
                frame = json.dumps(
                    render_top_json(
                        view, prev=prev,
                        interval=federator.interval if prev is not None else 0.0,
                        top=args.top,
                    ),
                    default=str,
                )
            else:
                frame = render_top(
                    view, prev=prev,
                    interval=federator.interval if prev is not None else 0.0,
                    top=args.top,
                )
                if not args.plain:
                    print("\x1b[2J\x1b[H", end="")
            print(frame, flush=True)
            if args.once:
                return 0
            prev = view
            time.sleep(max(0.0, federator.interval - (time.time() - t0)))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
