"""Declarative SLO engine + alert plane over the always-on metrics registry.

Everything upstream of this module *measures*; this module *judges*.
Operators declare objectives in a validated spec — JSON (or TOML for
``*.toml``) via ``OPTIONS["slo_path"]`` / ``FLOX_TPU_SLO_PATH``, with
built-in defaults when no path is set — across four kinds:

- **latency**: fraction of ``serve.request_ms`` observations at or under
  ``threshold_ms`` (bucket-granular against the shared log-spaced
  histogram edges; a ``tenant`` field reads that tenant's labeled
  histogram instead of the base series).
- **availability**: the typed ServeError taxonomy split into
  budget-burning (load shed, deadline, circuit-open fast-fail, device
  loss, watchdog) vs. excluded (drain rejections, client protocol errors
  — the replica did nothing wrong), over ``serve.requests``.
- **correctness**: fed by the canary prober (:func:`canary_loop`) — a
  background task issuing known-answer requests across the op matrix
  (inline reduce, fused multi-stat, resident-dataset hit, store
  append→query round-trip) and asserting bit-exact results. Canary
  traffic is billed under the reserved ``__canary__`` tenant and is
  excluded from every user-facing SLO.
- **freshness**: staleness of each open incremental store's last acked
  append, ticked once per evaluation against ``max_staleness_s``.

The error-budget ledger drives Google-SRE multi-window multi-burn-rate
evaluation: each rule pairs a short and a long window (defaults: 5m+1h at
14.4x for a fast-burn **page**, 6h+3d at 1x for a slow-burn **ticket**)
and breaches only when BOTH windows burn at or above the rule's rate —
the short window gates alert *reset lag*, the long window gates *noise*.
Alerts walk a pending → firing → resolved state machine; a page-severity
transition to firing triggers a flight dump plus an on-chip-capture hint
event, so the forensic record exists before an operator even looks.

Determinism: ``faults.slo_inject`` supplies a controllable clock and
synthetic SLI event bursts (plus canary-response corruption), so the
whole burn-rate lifecycle is testable without wall-clock sleeps. All
module state is registered in ``cache.clear_all`` / ``cache.stats``
(floxlint FLX008); surfaces are the ``/slo`` + ``/alerts`` endpoints,
``slo.*`` / ``alert.*`` / ``canary.*`` metrics, the
``python -m flox_tpu.telemetry slo`` CLI, and fleet federation.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from collections import deque
from types import MappingProxyType
from typing import Any

import numpy as np

# options as a module attribute, never from-bound: tests reload
# flox_tpu.options, and a from-import would read the pre-reload dict
from . import options, telemetry
from .metric_names import (
    CANARY_FAILURES,
    CANARY_OK,
    SERVE_BREAKER_FASTFAIL,
    SERVE_DEADLINE_EXCEEDED,
    SERVE_DEVICE_LOST,
    SERVE_REQUEST_MS,
    SERVE_REQUESTS,
    SERVE_SHED,
    SERVE_WATCHDOG_FIRED,
)
from .telemetry import CANARY_TENANT, METRICS

__all__ = [
    "CANARY_TENANT",
    "DEFAULT_SPEC",
    "alert_snapshot",
    "alerts",
    "canary_cycle",
    "canary_loop",
    "clear",
    "evaluate",
    "load_spec",
    "record_canary",
    "seed_gauges",
    "slo_stats",
    "validate_spec",
]

_KINDS = ("latency", "availability", "correctness", "freshness")
_SEVERITIES = ("page", "ticket")
#: sort/dedup order: a page outranks a ticket. Constants here are
#: MappingProxyType, not dict: module-level dicts are clearable STATE in
#: this codebase (FLX008 / cache.clear_all introspection) and these never
#: change
_SEVERITY_RANK = MappingProxyType({"page": 0, "ticket": 1})
#: alert-state sort order on /alerts and in federation
_STATE_RANK = MappingProxyType({"firing": 0, "pending": 1, "resolved": 2})

#: serve counters that burn the availability budget (the replica failed
#: the caller) — drain rejections and client protocol errors are excluded
#: by OMISSION here: they are either planned (drain) or the caller's bug
AVAILABILITY_BAD_COUNTERS = (
    SERVE_SHED,
    SERVE_DEADLINE_EXCEEDED,
    SERVE_BREAKER_FASTFAIL,
    SERVE_DEVICE_LOST,
    SERVE_WATCHDOG_FIRED,
)

#: the built-in objective set used when OPTIONS["slo_path"] is unset —
#: conservative targets an unconfigured replica can actually meet
DEFAULT_SPEC: MappingProxyType = MappingProxyType({
    "objectives": [
        {"name": "latency", "kind": "latency", "target": 0.99, "threshold_ms": 250.0},
        {"name": "availability", "kind": "availability", "target": 0.999},
        {"name": "correctness", "kind": "correctness", "target": 0.999},
        {"name": "freshness", "kind": "freshness", "target": 0.99, "max_staleness_s": 600.0},
    ],
    "windows": [
        {"name": "fast", "short_s": 300.0, "long_s": 3600.0, "burn_rate": 14.4, "severity": "page"},
        {"name": "slow", "short_s": 21600.0, "long_s": 259200.0, "burn_rate": 1.0, "severity": "ticket"},
    ],
})


# --------------------------------------------------------------------------
# engine state (all registered in cache.clear_all — floxlint FLX008)

#: parsed-spec cache: {"path": <str|None>, "spec": <validated spec>}
_SPEC_CACHE: dict[str, Any] = {}
#: (t, {objective name: (good, bad)}) cumulative-total snapshots, one per
#: evaluate() — window deltas subtract the newest snapshot old enough
_SNAPSHOT_RING: deque = deque(maxlen=4096)
#: (objective name, window rule name) -> alert row (the state machine)
_ALERT_TABLE: dict[tuple[str, str], dict] = {}
#: canary op -> {"probes", "failures", "last_ok", "last_error"}
_CANARY_LEDGER: dict[str, dict] = {}
#: freshness objective name -> [good ticks, bad ticks] cumulative
_FRESHNESS_LEDGER: dict[str, list] = {}
_LOCK = threading.RLock()


def clear() -> None:
    """Reset the whole SLO plane (``cache.clear_all`` calls this; the body
    references ``_SNAPSHOT_RING`` / ``_ALERT_TABLE`` / ``_CANARY_LEDGER`` /
    ``_FRESHNESS_LEDGER`` / ``_SPEC_CACHE`` directly for floxlint FLX008).
    ``slo.*`` / ``alert.*`` gauges die with the shared registry reset."""
    with _LOCK:
        _SNAPSHOT_RING.clear()
        _ALERT_TABLE.clear()
        _CANARY_LEDGER.clear()
        _FRESHNESS_LEDGER.clear()
        _SPEC_CACHE.clear()


# --------------------------------------------------------------------------
# spec loading + validation


def _fail(msg: str) -> None:
    raise ValueError(f"invalid SLO spec: {msg}")


def _validate_window(rule: Any, seen: set) -> dict:
    if not isinstance(rule, dict):
        _fail(f"window rule must be a table/object, got {type(rule).__name__}")
    name = rule.get("name")
    if not isinstance(name, str) or not name:
        _fail("window rule needs a non-empty string 'name'")
    if name in seen:
        _fail(f"duplicate window rule name {name!r}")
    seen.add(name)
    out = {"name": name}
    for key in ("short_s", "long_s", "burn_rate"):
        v = rule.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) or not v > 0:
            _fail(f"window {name!r} needs {key} > 0, got {v!r}")
        out[key] = float(v)
    if not out["short_s"] < out["long_s"]:
        _fail(f"window {name!r} needs short_s < long_s")
    sev = rule.get("severity", "ticket")
    if sev not in _SEVERITIES:
        _fail(f"window {name!r} severity must be one of {_SEVERITIES}, got {sev!r}")
    out["severity"] = sev
    extra = set(rule) - {"name", "short_s", "long_s", "burn_rate", "severity"}
    if extra:
        _fail(f"window {name!r} has unknown keys {sorted(extra)}")
    return out


def validate_spec(spec: Any) -> dict:
    """Normalize + validate a spec, raising ``ValueError`` (never a silent
    fallback — a typo'd objective must not evaluate as vacuously healthy)."""
    if not isinstance(spec, dict):
        _fail(f"top level must be a table/object, got {type(spec).__name__}")
    extra = set(spec) - {"objectives", "windows"}
    if extra:
        _fail(f"unknown top-level keys {sorted(extra)}")
    windows = [_validate_window(r, set()) for r in _as_rules(spec.get("windows"))]
    objectives = spec.get("objectives")
    if not isinstance(objectives, list) or not objectives:
        _fail("'objectives' must be a non-empty list")
    out_objs: list[dict] = []
    names: set[str] = set()
    for obj in objectives:
        if not isinstance(obj, dict):
            _fail(f"objective must be a table/object, got {type(obj).__name__}")
        name = obj.get("name")
        if not isinstance(name, str) or not name or any(c in name for c in "|= \t"):
            _fail(f"objective needs a label-safe non-empty 'name', got {name!r}")
        if name in names:
            _fail(f"duplicate objective name {name!r}")
        names.add(name)
        kind = obj.get("kind")
        if kind not in _KINDS:
            _fail(f"objective {name!r} kind must be one of {_KINDS}, got {kind!r}")
        target = obj.get("target")
        if (
            not isinstance(target, (int, float))
            or isinstance(target, bool)
            or not 0 < float(target) < 1
        ):
            _fail(f"objective {name!r} needs 0 < target < 1, got {target!r}")
        row = {"name": name, "kind": kind, "target": float(target)}
        allowed = {"name", "kind", "target", "windows"}
        if kind == "latency":
            thr = obj.get("threshold_ms")
            if not isinstance(thr, (int, float)) or isinstance(thr, bool) or not thr > 0:
                _fail(f"latency objective {name!r} needs threshold_ms > 0, got {thr!r}")
            row["threshold_ms"] = float(thr)
            allowed |= {"threshold_ms", "tenant"}
            tenant = obj.get("tenant")
            if tenant is not None:
                if not isinstance(tenant, str) or not tenant:
                    _fail(f"latency objective {name!r} tenant must be a non-empty string")
                row["tenant"] = tenant
        elif kind == "freshness":
            stale = obj.get("max_staleness_s")
            if not isinstance(stale, (int, float)) or isinstance(stale, bool) or not stale > 0:
                _fail(f"freshness objective {name!r} needs max_staleness_s > 0, got {stale!r}")
            row["max_staleness_s"] = float(stale)
            allowed |= {"max_staleness_s"}
        extra = set(obj) - allowed
        if extra:
            _fail(f"objective {name!r} has unknown keys {sorted(extra)}")
        own = obj.get("windows")
        if own is not None:
            row["windows"] = [_validate_window(r, set()) for r in _as_rules(own)]
        out_objs.append(row)
    return {"objectives": out_objs, "windows": windows}


def _as_rules(windows: Any) -> list:
    if windows is None:
        return [dict(r) for r in DEFAULT_SPEC["windows"]]
    if not isinstance(windows, list) or not windows:
        _fail("'windows' must be a non-empty list of rules")
    return windows


def _tomllib():
    """The stdlib TOML parser (3.11+), falling back to ``tomli`` where
    present; absent both, a ``*.toml`` spec is a clear ValueError telling
    the operator to use JSON — never a bare ModuleNotFoundError."""
    try:
        import tomllib
    except ModuleNotFoundError:
        try:
            import tomli as tomllib  # type: ignore[no-redef]
        except ModuleNotFoundError:
            _fail(
                "TOML specs need Python >= 3.11 (tomllib) or the tomli "
                "package; write the spec as JSON instead"
            )
    return tomllib


def load_spec(path: Any = None, *, force: bool = False) -> dict:
    """The active validated spec: ``path`` (default ``OPTIONS["slo_path"]``)
    parsed as TOML for ``*.toml`` else JSON, or :data:`DEFAULT_SPEC` when no
    path is configured. Cached until the configured path changes (tests and
    a reloaded config pass ``force=True``). Raises ``ValueError`` for an
    unreadable or invalid spec — loudly, at the surface that asked."""
    if path is None:
        path = options.OPTIONS["slo_path"]
    key = str(path) if path is not None else None
    with _LOCK:
        if not force and _SPEC_CACHE.get("path", ()) == key and "spec" in _SPEC_CACHE:
            return _SPEC_CACHE["spec"]
    if key is None:
        spec = validate_spec(json.loads(json.dumps(dict(DEFAULT_SPEC))))
    else:
        try:
            if key.endswith(".toml"):
                tomllib = _tomllib()
                with open(key, "rb") as fh:  # noqa: FLX015 — one-shot KB-scale config read, cached for the process lifetime
                    raw = tomllib.load(fh)
            else:
                with open(key, encoding="utf-8") as fh:  # noqa: FLX015 — one-shot KB-scale config read, cached for the process lifetime
                    raw = json.load(fh)
        except ValueError as exc:  # JSON/TOML syntax errors
            raise ValueError(f"invalid SLO spec: cannot parse {key}: {exc}") from exc
        except OSError as exc:
            raise ValueError(f"invalid SLO spec: cannot read {key}: {exc}") from exc
        spec = validate_spec(raw)
    with _LOCK:
        _SPEC_CACHE["path"] = key
        _SPEC_CACHE["spec"] = spec
    return spec


# --------------------------------------------------------------------------
# SLI collectors — cumulative (good, bad) totals per objective


def _now() -> float:
    from . import faults

    t = faults.slo_now()
    return time.time() if t is None else t


def _latency_totals(obj: dict) -> tuple[float, float]:
    name = SERVE_REQUEST_MS
    if obj.get("tenant"):
        name = f"{SERVE_REQUEST_MS}|tenant={telemetry.tenant_label(obj['tenant'], register=False)}"
    hist = METRICS.histograms().get(name)
    if not hist:
        return 0.0, 0.0
    good = float(
        sum(
            n
            for edge, n in zip(telemetry.HIST_EDGES_MS, hist["counts"])
            if edge <= obj["threshold_ms"]
        )
    )
    return good, float(hist["count"]) - good


def _availability_totals(obj: dict) -> tuple[float, float]:
    bad = float(sum(METRICS.get(c) for c in AVAILABILITY_BAD_COUNTERS))
    total = float(METRICS.get(SERVE_REQUESTS))
    return max(0.0, total - bad), bad


def _correctness_totals(obj: dict) -> tuple[float, float]:
    return float(METRICS.get(CANARY_OK)), float(METRICS.get(CANARY_FAILURES))


def _freshness_totals(obj: dict) -> tuple[float, float]:
    """Tick the freshness ledger once: each open store contributes one
    good/bad event per evaluation depending on its append staleness. The
    canary's own store is reserved-tenant traffic and excluded."""
    led = _FRESHNESS_LEDGER.setdefault(obj["name"], [0, 0])
    try:
        from .serve import stores as serve_stores

        staleness = serve_stores.staleness_by_store(now=_now())
    except Exception:  # noqa: BLE001 — a serve layer that never imported
        # (pure-library use) must not fail SLO evaluation
        staleness = {}
    for store_name, stale_s in staleness.items():
        if store_name.startswith(CANARY_TENANT):
            continue
        led[1 if stale_s > obj["max_staleness_s"] else 0] += 1
    return float(led[0]), float(led[1])


_COLLECTORS = MappingProxyType({
    "latency": _latency_totals,
    "availability": _availability_totals,
    "correctness": _correctness_totals,
    "freshness": _freshness_totals,
})


def _collect(obj: dict) -> tuple[float, float]:
    good, bad = _COLLECTORS[obj["kind"]](obj)
    from . import faults

    inj_good, inj_bad = faults.slo_injected(obj["name"])
    return good + inj_good, bad + inj_bad


# --------------------------------------------------------------------------
# burn-rate math + the alert state machine


def _window_delta(
    name: str, now: float, window_s: float, totals: tuple[float, float]
) -> tuple[float, float]:
    """(good, bad) accrued inside the trailing window: current totals minus
    the newest ring snapshot at least ``window_s`` old (falling back to the
    oldest — a partial window — while history is shorter than the window).
    Deltas clamp at 0 so counter resets read as quiet, not as burn."""
    base: tuple[float, float] = (0.0, 0.0)
    baseline_t = None
    for t, snap in _SNAPSHOT_RING:
        if t <= now - window_s:
            base = snap.get(name, (0.0, 0.0))
            baseline_t = t
        else:
            break
    if baseline_t is None and _SNAPSHOT_RING:
        t, snap = _SNAPSHOT_RING[0]
        base = snap.get(name, (0.0, 0.0))
    return max(0.0, totals[0] - base[0]), max(0.0, totals[1] - base[1])


def _burn(name: str, now: float, window_s: float, totals, err_budget: float) -> float:
    """The window's burn rate: (bad fraction) / (error budget). 1.0 spends
    the budget exactly over the SLO period; 0 when the window saw nothing
    (no traffic is healthy, not unknown — idle replicas must not page)."""
    good, bad = _window_delta(name, now, window_s, totals)
    total = good + bad
    if total <= 0:
        return 0.0
    return (bad / total) / err_budget


def _step_alert(obj: dict, rule: dict, breach: bool, burns: dict, now: float) -> None:
    """One state-machine step for (objective, rule). Transitions:
    absent/resolved --breach--> pending --breach--> firing --clear-->
    resolved; a pending that clears before confirming is dropped (a
    one-evaluation blip never reaches an operator)."""
    key = (obj["name"], rule["name"])
    held = _ALERT_TABLE.get(key)
    if breach:
        if held is None or held["state"] == "resolved":
            _ALERT_TABLE[key] = {
                "objective": obj["name"],
                "window": rule["name"],
                "severity": rule["severity"],
                "state": "pending",
                "since": now,
                "fired_at": None,
                "resolved_at": None,
                **burns,
            }
            METRICS.inc("alert.pending_total")
        elif held["state"] == "pending":
            held.update(state="firing", fired_at=now, **burns)
            METRICS.inc("alert.fired")
            METRICS.inc(f"alert.fired|objective={obj['name']}")
            telemetry.event(
                "alert-firing",
                objective=obj["name"],
                window=rule["name"],
                severity=rule["severity"],
                burn_short=burns["burn_short"],
                burn_long=burns["burn_long"],
            )
            if rule["severity"] == "page":
                METRICS.inc("alert.pages")
                # the forensic record should exist BEFORE the operator
                # arrives: dump the flight recorder and hint at the
                # on-chip capture surface for the device-side view
                telemetry.flight_dump(reason=f"alert:{obj['name']}:{rule['name']}")
                telemetry.event(
                    "capture-hint",
                    objective=obj["name"],
                    hint="page-severity alert: consider /debug/profile for an on-chip capture",
                )
        else:  # still firing: refresh the burn numbers operators see
            held.update(**burns)
    elif held is not None:
        if held["state"] == "pending":
            del _ALERT_TABLE[key]
        elif held["state"] == "firing":
            held.update(state="resolved", resolved_at=now, **burns)
            METRICS.inc("alert.resolved_total")
            telemetry.event(
                "alert-resolved", objective=obj["name"], window=rule["name"]
            )


def evaluate(now: float | None = None) -> dict:
    """One evaluation pass: collect cumulative SLI totals, snapshot them
    into the window ring, compute every rule's short+long burn rates, step
    the alert state machine, and publish ``slo.*``/``alert.*`` gauges.
    Returns the ``/slo`` payload. Raises ``ValueError`` for a bad spec."""
    spec = load_spec()
    if now is None:
        now = _now()
    with _LOCK:
        totals = {obj["name"]: _collect(obj) for obj in spec["objectives"]}
        _SNAPSHOT_RING.append((now, totals))
        payload_objs = []
        for obj in spec["objectives"]:
            err_budget = 1.0 - obj["target"]
            rules = obj.get("windows") or spec["windows"]
            good, bad = totals[obj["name"]]
            windows = []
            fast_burn = 0.0
            budget_window = max(r["long_s"] for r in rules)
            for rule in rules:
                burn_short = _burn(obj["name"], now, rule["short_s"], totals[obj["name"]], err_budget)
                burn_long = _burn(obj["name"], now, rule["long_s"], totals[obj["name"]], err_budget)
                breach = burn_short >= rule["burn_rate"] and burn_long >= rule["burn_rate"]
                fast_burn = max(fast_burn, burn_short)
                burns = {"burn_short": round(burn_short, 4), "burn_long": round(burn_long, 4)}
                _step_alert(obj, rule, breach, burns, now)
                windows.append(
                    {
                        "window": rule["name"],
                        "severity": rule["severity"],
                        "short_s": rule["short_s"],
                        "long_s": rule["long_s"],
                        "burn_threshold": rule["burn_rate"],
                        "breach": breach,
                        **burns,
                    }
                )
            wg, wb = _window_delta(obj["name"], now, budget_window, totals[obj["name"]])
            ratio = (wb / (wg + wb)) if (wg + wb) > 0 else 0.0
            budget_remaining = round(1.0 - ratio / err_budget, 4)
            firing = any(
                a["state"] == "firing" and a["objective"] == obj["name"]
                for a in _ALERT_TABLE.values()
            )
            payload_objs.append(
                {
                    "name": obj["name"],
                    "kind": obj["kind"],
                    "target": obj["target"],
                    "good": good,
                    "bad": bad,
                    "budget_remaining": budget_remaining,
                    "healthy": not firing,
                    "windows": windows,
                }
            )
            METRICS.set_gauge(f"slo.budget_remaining|objective={obj['name']}", budget_remaining)
            METRICS.set_gauge(f"slo.burn_rate|objective={obj['name']}", round(fast_burn, 4))
        alert_rows = _alert_rows()
        firing = sum(1 for a in alert_rows if a["state"] == "firing")
        pending = sum(1 for a in alert_rows if a["state"] == "pending")
        METRICS.set_gauge("alert.firing", float(firing))
        METRICS.set_gauge("alert.pending", float(pending))
        METRICS.set_gauge("slo.objectives", float(len(payload_objs)))
        METRICS.inc("slo.evaluations")
        return {
            "healthy": firing == 0,
            "evaluated_at": now,
            "spec_path": _SPEC_CACHE.get("path"),
            "objectives": payload_objs,
            "alerts": alert_rows,
        }


def _alert_rows() -> list[dict]:
    return sorted(
        (dict(a) for a in _ALERT_TABLE.values()),
        key=lambda a: (
            _STATE_RANK.get(a["state"], 9),
            _SEVERITY_RANK.get(a["severity"], 9),
            a["objective"],
            a["window"],
        ),
    )


def alerts() -> list[dict]:
    """The current alert rows (firing first, pages before tickets) WITHOUT
    re-evaluating — the cheap read for dumps and stats panels."""
    with _LOCK:
        return _alert_rows()


def alert_snapshot() -> dict:
    """Compact alert-state summary for flight-dump headers: state ->
    ``objective/window[severity]`` labels, next to the breaker snapshot."""
    with _LOCK:
        out: dict[str, list] = {"firing": [], "pending": [], "resolved": []}
        for a in _alert_rows():
            out.setdefault(a["state"], []).append(
                f"{a['objective']}/{a['window']}[{a['severity']}]"
            )
        return out


def slo_stats() -> dict:
    """The SLO plane's ``cache.stats()`` panel — module-state snapshot
    only, never an evaluation (stats must not move the alert machine)."""
    with _LOCK:
        rows = _alert_rows()
        return {
            "spec_path": _SPEC_CACHE.get("path"),
            "snapshots": len(_SNAPSHOT_RING),
            "alerts": {
                state: sum(1 for a in rows if a["state"] == state)
                for state in ("firing", "pending", "resolved")
            },
            "canary": {
                "probes": int(sum(r["probes"] for r in _CANARY_LEDGER.values())),
                "failures": int(sum(r["failures"] for r in _CANARY_LEDGER.values())),
            },
        }


def seed_gauges() -> None:
    """Run one evaluation at metrics-server start so ``/slo`` and the
    budget gauges answer from the first scrape; a bad configured spec is
    surfaced as an event + counter here, never a server-start failure
    (the /slo endpoint will re-raise it with a 500 for the operator)."""
    try:
        evaluate()
    except ValueError as exc:
        METRICS.inc("slo.spec_errors")
        telemetry.event("slo-spec-error", error=str(exc)[:200])


# --------------------------------------------------------------------------
# canary prober: known-answer requests across the op matrix

#: reserved names for canary resident state; the leading "__canary__"
#: keeps them out of freshness SLOs and lets dashboards filter them
CANARY_DATASET = "__canary__"
CANARY_STORE = "__canary__"

#: power-of-two payload with exact float sums: sum -> [3, 12],
#: count -> [2, 2], mean -> [1.5, 6] — every comparison is bit-exact
_CANARY_ARRAY = (1.0, 2.0, 4.0, 8.0)
_CANARY_BY = (0, 0, 1, 1)
_EXPECTED = MappingProxyType({
    "sum": np.asarray([3.0, 12.0]),
    "count": np.asarray([2, 2]),
    "mean": np.asarray([1.5, 6.0]),
})


def record_canary(op: str, ok: bool, error: str | None = None) -> None:
    """Record one probe verdict: the canary ledger + ``canary.*`` counters
    feeding the correctness SLO. Failures never touch the serve error
    taxonomy, so a wrong answer burns the correctness budget while the
    availability SLO correctly reads the replica as up."""
    with _LOCK:
        row = _CANARY_LEDGER.setdefault(
            op, {"probes": 0, "failures": 0, "last_ok": None, "last_error": None}
        )
        row["probes"] += 1
        row["last_ok"] = bool(ok)
        if not ok:
            row["failures"] += 1
            row["last_error"] = error
    METRICS.inc("canary.probes")
    if ok:
        METRICS.inc("canary.ok")
    else:
        METRICS.inc("canary.failures")
        METRICS.inc(f"canary.failures|op={op}")
        telemetry.event("canary-failure", op=op, error=(error or "")[:200])


def _verdict(op: str, got: Any, want: np.ndarray) -> bool:
    """Bit-exact compare, after letting an installed faults plan corrupt
    the received value (how tests/CI prove a wrong answer is caught)."""
    from . import faults

    arr = np.asarray(got)
    if faults.slo_canary_corrupt(op):
        arr = arr + 1
    ok = arr.shape == want.shape and bool(np.array_equal(arr, want))
    record_canary(op, ok, None if ok else f"expected {want.tolist()}, got {arr.tolist()}")
    return ok


async def _probe_reduce(dispatcher, cycle: int) -> None:
    from .serve.dispatcher import AggregationRequest

    res = await dispatcher.submit(
        AggregationRequest(
            func="sum",
            array=np.asarray(_CANARY_ARRAY),
            by=np.asarray(_CANARY_BY),
            tenant=CANARY_TENANT,
            request_id=f"canary-reduce-{cycle}",
        )
    )
    _verdict("reduce", res.result, _EXPECTED["sum"])


async def _probe_multistat(dispatcher, cycle: int) -> None:
    from .serve.dispatcher import AggregationRequest

    res = await dispatcher.submit(
        AggregationRequest(
            func=("sum", "count", "mean"),
            array=np.asarray(_CANARY_ARRAY),
            by=np.asarray(_CANARY_BY),
            tenant=CANARY_TENANT,
            request_id=f"canary-multistat-{cycle}",
        )
    )
    out = res.result
    ok = isinstance(out, dict) and all(
        f in out and np.asarray(out[f]).shape == want.shape and np.array_equal(out[f], want)
        for f, want in _EXPECTED.items()
    )
    from . import faults

    if faults.slo_canary_corrupt("multistat"):
        ok = False
    record_canary("multistat", ok, None if ok else f"fused stats mismatch: {out!r:.200}")


async def _probe_dataset(dispatcher, cycle: int) -> None:
    from .serve import registry
    from .serve.dispatcher import AggregationRequest

    try:
        registry.resolve(CANARY_DATASET)
    except Exception:  # noqa: BLE001 — any resolve failure (unknown name,
        # post-clear_all) means (re)pin the canary dataset
        await asyncio.to_thread(
            registry.put,
            CANARY_DATASET,
            np.asarray(_CANARY_ARRAY),
            np.asarray(_CANARY_BY),
        )
    res = await dispatcher.submit(
        AggregationRequest(
            func="sum",
            dataset=CANARY_DATASET,
            tenant=CANARY_TENANT,
            request_id=f"canary-dataset-{cycle}",
        )
    )
    _verdict("dataset", res.result, _EXPECTED["sum"])


async def _probe_store(dispatcher, cycle: int) -> bool:
    """Store append→query round-trip; skipped (returns False) without a
    configured store root. The constant slab id makes every cycle after
    the first an exactly-once REPLAY, so the known answer never drifts."""
    if not options.OPTIONS["store_root"]:
        return False
    from .serve import stores as serve_stores

    await asyncio.to_thread(
        serve_stores.append,
        CANARY_STORE,
        np.asarray(_CANARY_BY),
        np.asarray(_CANARY_ARRAY),
        slab_id="canary-slab-0",
        create={"funcs": ["sum"], "size": 2},
    )
    out = await asyncio.to_thread(serve_stores.query, CANARY_STORE, ["sum"])
    _verdict("store", out["sum"], _EXPECTED["sum"])
    return True


_PROBES = (
    ("reduce", _probe_reduce),
    ("multistat", _probe_multistat),
    ("dataset", _probe_dataset),
    ("store", _probe_store),
)


async def canary_cycle(dispatcher, cycle: int = 0) -> dict:
    """One pass over the op matrix. Returns op -> verdict (``None`` for a
    skipped probe). A probe that errors records a correctness failure —
    unless the replica is draining, which is planned downtime for the
    canary too (it neither passes nor fails)."""
    verdicts: dict[str, bool | None] = {}
    for op, probe in _PROBES:
        before = _probe_count(op)
        try:
            skipped = await probe(dispatcher, cycle) is False and op == "store"
            if skipped:
                verdicts[op] = None
                continue
        except asyncio.CancelledError:
            raise
        # noqa: FLX006 — not a retry loop: ops are independent probes, and
        # a probe error IS the signal (correctness failure), except drain
        except Exception as exc:  # noqa: FLX006
            if getattr(exc, "code", None) == "draining":
                verdicts[op] = None
                continue
            record_canary(op, False, f"{type(exc).__name__}: {exc}")
            verdicts[op] = False
            continue
        verdicts[op] = _probe_count(op) > before and _last_ok(op)
    return verdicts


def _probe_count(op: str) -> int:
    with _LOCK:
        row = _CANARY_LEDGER.get(op)
        return int(row["probes"]) if row else 0


def _last_ok(op: str) -> bool:
    with _LOCK:
        row = _CANARY_LEDGER.get(op)
        return bool(row and row["last_ok"])


async def canary_loop(dispatcher, interval: float) -> None:
    """The background prober ``python -m flox_tpu.serve`` runs when
    ``--canary-interval`` / ``FLOX_TPU_SLO_CANARY_INTERVAL`` is > 0: one
    :func:`canary_cycle` + one :func:`evaluate` per period. Never raises
    out (a broken probe must not take serving down); cancelled on drain."""
    cycle = 0
    while True:
        cycle += 1
        try:
            await canary_cycle(dispatcher, cycle)
            evaluate()
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: FLX006 — not a retry of one
            # failed operation: each cycle is an independent probe pass,
            # and the prober outliving a transient error is the point
            telemetry.record_serve_error(exc, what="canary cycle")
        await asyncio.sleep(interval)
