"""Resharding helpers: lay data out so groups are shard-local (L3).

Parity target: /root/reference/flox/rechunk.py — ``rechunk_for_blockwise``
(rechunk.py:158-223, optimal chunk boundaries for sorted labels) and
``rechunk_for_cohorts`` (rechunk.py:64-155).

TPU rethink: dask chunks can have arbitrary sizes, so the reference *moves
chunk boundaries* to group boundaries. Mesh shards are equal-sized, so the
equivalent transformation is a **permutation + padding**: order elements by
group, assign whole groups to shards balancing element counts, and pad each
shard to a common length with missing labels (code -1, which every kernel
ignores). The result feeds ``method='blockwise'`` — each group's members
live entirely on one shard, so no collective combine is needed, and order
statistics (median/quantile/mode) become mesh-executable.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any

import numpy as np

logger = logging.getLogger("flox_tpu.rechunk")

__all__ = ["reshard_for_blockwise", "BlockwiseLayout", "rechunk_for_blockwise", "rechunk_for_cohorts"]


@dataclass(frozen=True)
class BlockwiseLayout:
    """A shard-local-groups layout produced by :func:`reshard_for_blockwise`.

    ``permutation``: host int64 array, indices into the original trailing
    axis for each padded slot (-1 = padding).
    ``codes``: group codes per padded slot (-1 = padding).
    ``n_shards`` / ``shard_len``: the padded geometry.
    """

    permutation: np.ndarray
    codes: np.ndarray
    n_shards: int
    shard_len: int

    def apply(self, array):
        """Gather ``array`` (..., N) into the padded blockwise layout."""
        import jax.numpy as jnp

        from . import utils

        arr = utils.asarray_device(array)
        perm = jnp.asarray(np.where(self.permutation < 0, 0, self.permutation))
        out = jnp.take(arr, perm, axis=-1)
        invalid = jnp.asarray(self.permutation < 0)
        if jnp.issubdtype(out.dtype, jnp.floating):
            out = jnp.where(invalid, jnp.nan, out)
        return out


def reshard_for_blockwise(codes: np.ndarray, n_shards: int) -> BlockwiseLayout:
    """Compute a permutation that makes every group shard-local.

    Greedy longest-processing-time assignment of groups to shards (balanced
    element counts), then per-shard concatenation with padding to the max
    shard length. The reference's analogue moves dask chunk boundaries to
    group boundaries (rechunk.py:29-61); equal-size mesh shards need the
    permutation form instead.
    """
    codes = np.asarray(codes).reshape(-1)
    n = codes.shape[0]
    valid = codes >= 0
    uniq, counts = np.unique(codes[valid], return_counts=True)

    # greedy LPT: biggest group to the least-loaded shard
    order = np.argsort(counts)[::-1]
    loads = np.zeros(n_shards, dtype=np.int64)
    assignment = {}
    for gi in order:
        s = int(np.argmin(loads))
        assignment[uniq[gi]] = s
        loads[s] += counts[gi]
    shard_len = int(loads.max()) if len(uniq) else 1

    # build per-shard index lists (stable within group: original order kept).
    # One stable sort by code gives every group's positions contiguously —
    # O(n log n) total instead of a per-group O(n) scan.
    perm = np.full((n_shards, shard_len), -1, dtype=np.int64)
    out_codes = np.full((n_shards, shard_len), -1, dtype=np.int64)
    cursors = np.zeros(n_shards, dtype=np.int64)
    valid_idx = np.flatnonzero(valid)
    by_code = valid_idx[np.argsort(codes[valid_idx], kind="stable")]
    starts = np.concatenate([[0], np.cumsum(counts)])
    for gi, g in enumerate(uniq):
        s = assignment[g]
        idx = by_code[starts[gi] : starts[gi + 1]]
        c = cursors[s]
        perm[s, c : c + idx.size] = idx
        out_codes[s, c : c + idx.size] = g
        cursors[s] += idx.size

    logger.debug(
        "reshard_for_blockwise: %d groups over %d shards, shard_len=%d (pad %.1f%%)",
        len(uniq), n_shards, shard_len,
        100.0 * (n_shards * shard_len - int(valid.sum())) / max(n_shards * shard_len, 1),
    )
    return BlockwiseLayout(
        permutation=perm.reshape(-1),
        codes=out_codes.reshape(-1),
        n_shards=n_shards,
        shard_len=shard_len,
    )


def rechunk_for_blockwise(
    array: Any, axis: int, labels: Any, n_shards: int | None = None
) -> tuple:
    """Convenience wrapper mirroring the reference's public name
    (rechunk.py:158-223): returns ``(resharded_array, resharded_codes)``
    ready for ``groupby_reduce(..., method='blockwise')``.

    Auto-application thresholds (OPTIONS['rechunk_blockwise_*'], parity:
    options.py:9-18) are the caller's concern; this always reshards.
    """
    import jax

    from . import factorize as fct

    if n_shards is None:
        n_shards = len(jax.devices())
    codes, groups = fct.factorize_single(np.asarray(labels), None, sort=True)
    layout = reshard_for_blockwise(codes, n_shards)
    import numpy as _np

    arr = _np.moveaxis(_np.asarray(array), axis, -1) if axis not in (-1, np.ndim(array) - 1) else array
    return layout.apply(arr), layout.codes, groups


def rechunk_for_cohorts(
    array: Any,
    axis: int,
    labels: Any,
    force_new_chunk_at: Any,
    chunksize: int | None = None,
    debug: bool = False,
) -> tuple[int, ...] | tuple[tuple[int, ...], list[int]]:
    """Chunk boundaries anchored at label-pattern starts (parity:
    rechunk.py:64-155).

    For periodic labels (day-of-year, month), placing a boundary wherever a
    label in ``force_new_chunk_at`` begins makes every chunk hold one period
    segment, so the same label subset recurs in the same chunk position — the
    layout that makes cohorts maximally effective. Returns the chunk-length
    tuple (feed it to cohorts.find_group_cohorts, or use the lengths as
    shard sizes after reshard_for_blockwise-style padding).
    """
    labels = np.asarray(labels).reshape(-1)
    n = labels.shape[0]
    if array is not None:
        ax_len = np.shape(array)[axis]
        if ax_len != n:
            raise ValueError(
                f"labels (length {n}) do not align with array axis {axis} (length {ax_len})"
            )
    anchors = np.atleast_1d(np.asarray(force_new_chunk_at))
    is_anchor = np.isin(labels, anchors)
    # boundary at the first position of every run of an anchor label
    starts = np.flatnonzero(is_anchor & np.r_[True, ~is_anchor[:-1]])
    anchor_bounds = [0]
    for pos in starts:
        if pos == 0:
            continue
        # hysteresis: keep chunks near the target size (parity: the
        # reference's chunksize tolerance, rechunk.py:104-139)
        if chunksize is not None and (pos - anchor_bounds[-1]) < max(1, chunksize // 2):
            continue
        anchor_bounds.append(int(pos))
    anchor_bounds.append(n)
    # subdivide within periods: chunks at the SAME offset of every period
    # then hold the same label subset — that repetition is what makes
    # cohorts effective (one anchor-to-anchor chunk would hold the whole
    # cycle and degrade to map-reduce). Default: ~4 chunks per period.
    if chunksize is None and len(anchor_bounds) > 2:
        min_period = min(b - a for a, b in zip(anchor_bounds[:-1], anchor_bounds[1:]))
        chunksize = max(1, min_period // 4)
    boundaries = [0]
    for a, b in zip(anchor_bounds[:-1], anchor_bounds[1:]):
        seg = b - a
        if chunksize is not None and seg > chunksize:
            nparts = -(-seg // chunksize)
            for p in range(1, nparts):
                boundaries.append(a + (seg * p) // nparts)
        if b != boundaries[-1]:
            boundaries.append(b)
    chunks = tuple(b - a for a, b in zip(boundaries[:-1], boundaries[1:]) if b > a)
    logger.debug(
        "rechunk_for_cohorts: %d chunks, sizes %s...", len(chunks), chunks[:5]
    )
    if debug:
        return chunks, boundaries
    return chunks
