"""The "numpy" engine: host-side grouped reductions without JAX (L1).

Same plugin signature as the jax engine (kernels.py). This is the analogue
of the reference's numpy_groupies-backed engine (aggregate_npg.py:7-126) but
written directly on numpy primitives: ``ufunc.at`` scatter-reduces and
``bincount``. It exists for (a) small host arrays where jit dispatch isn't
worth it, (b) an independent implementation for cross-checking the jax
engine, (c) parity with the reference's multi-engine architecture.

Arrays are (..., N) with ``group_idx`` (N,), code -1 = missing; returns
(..., size) like the jax engine.
"""

from __future__ import annotations

import numpy as np

__all__ = ["KERNELS", "generic_kernel"]


def _acc_dtype(dt):
    """f32 accumulation for sub-f32 floats (mirrors kernels._acc_dtype):
    f16/bf16 running sums and counts saturate at the narrow mantissa.
    bfloat16 registers with numpy as kind 'V', so match it by name."""
    dt = np.dtype(dt)
    if (dt.kind == "f" and dt.itemsize < 4) or dt.name == "bfloat16":
        return np.dtype(np.float32)
    return dt


def _prep(group_idx, array):
    """Transpose to (N, ...) and drop missing labels from the scatter."""
    codes = np.asarray(group_idx).reshape(-1).astype(np.int64)
    data = np.moveaxis(np.asarray(array), -1, 0)
    valid = codes >= 0
    return codes, data, valid


def _scatter(ufunc, codes, data, valid, size, init, dtype=None):
    out = np.full((size,) + data.shape[1:], init, dtype=dtype or data.dtype)
    ufunc.at(out, codes[valid], data[valid])
    return out


def _apply_fill(out, codes, valid, size, fill_value, identity=None):
    """Replace groups with no labelled elements by ``fill_value`` (shared by
    the add-like, count, and bool kernels so promotion rules stay aligned).
    ``out`` is (size, ...); returns possibly-promoted array."""
    if fill_value is None or (identity is not None and fill_value == identity):
        return out
    present = np.bincount(codes[valid], minlength=size) > 0
    present = np.broadcast_to(
        present.reshape((size,) + (1,) * (out.ndim - 1)), out.shape
    )
    inexact = np.issubdtype(out.dtype, np.floating) or np.issubdtype(
        out.dtype, np.complexfloating
    )
    if _nanlike(fill_value) and not inexact:
        out = out.astype(np.float64)
    return np.where(present, out, fill_value)


def _nanlike(v) -> bool:
    from . import utils as _u

    return _u.is_nan_fill(v)


_NAT_INT = np.iinfo(np.int64).min  # NaT viewed as int64 (core passes nat=True)


def _nan_mask(data, nat=False):
    if np.issubdtype(data.dtype, np.floating) or np.issubdtype(data.dtype, np.complexfloating):
        return ~np.isnan(data)
    if nat and np.issubdtype(data.dtype, np.signedinteger):
        return data != _NAT_INT
    return None


def _make_addlike(ufunc, identity, skipna):
    def kernel(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
        codes, data, valid = _prep(group_idx, array)
        mask = _nan_mask(data, kw.get("nat", False)) if skipna else None
        if mask is not None:
            data = np.where(mask, data, identity)
        if dtype is not None:
            data = data.astype(dtype, copy=False)
        out_dtype = data.dtype
        acc = _acc_dtype(out_dtype)
        out = _scatter(ufunc, codes, data.astype(acc, copy=False), valid, size, identity, acc)
        out = _apply_fill(out, codes, valid, size, fill_value, identity)
        if out.dtype == acc and acc != out_dtype:
            out = out.astype(out_dtype)
        return np.moveaxis(out, 0, -1)

    return kernel


sum_ = _make_addlike(np.add, 0, skipna=False)
nansum = _make_addlike(np.add, 0, skipna=True)
prod = _make_addlike(np.multiply, 1, skipna=False)
nanprod = _make_addlike(np.multiply, 1, skipna=True)


def _make_minmax(ufunc, is_max, skipna):
    def kernel(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
        codes, data, valid = _prep(group_idx, array)
        if dtype is not None:
            data = data.astype(dtype, copy=False)
        mask = _nan_mask(data, kw.get("nat", False))
        isfloat = np.issubdtype(data.dtype, np.floating)
        if isfloat:
            init = -np.inf if is_max else np.inf
        elif np.issubdtype(data.dtype, np.integer):
            info = np.iinfo(data.dtype)
            init = info.min if is_max else info.max
        else:
            init = False if is_max else True
        missing_marker = np.nan if isfloat else _NAT_INT
        absorb = init if isfloat else (np.iinfo(data.dtype).max if is_max else np.iinfo(data.dtype).min) if np.issubdtype(data.dtype, np.integer) else init
        work = data
        if mask is not None:
            work = np.where(mask, data, init if skipna else absorb)
        out = _scatter(ufunc, codes, work, valid, size, init)
        if mask is not None and not skipna:
            has_nan = np.zeros((size,) + data.shape[1:], dtype=bool)
            np.logical_or.at(has_nan, codes[valid], ~mask[valid])
            out = np.where(has_nan, missing_marker, out)
        if skipna and mask is not None:
            cnt = np.zeros((size,) + data.shape[1:], dtype=np.intp)
            np.add.at(cnt, codes[valid], mask[valid].astype(np.intp))
            present = cnt > 0
        else:
            present = np.bincount(codes[valid], minlength=size) > 0
        fv = fill_value
        if fv is None:
            fv = np.nan if isfloat else init
        inexact = np.issubdtype(out.dtype, np.floating) or np.issubdtype(
            out.dtype, np.complexfloating
        )
        if _nanlike(fv) and not inexact:
            out = out.astype(np.float64)
        out = np.where(
            np.broadcast_to(
                present.reshape(present.shape + (1,) * (out.ndim - present.ndim)), out.shape
            ),
            out,
            fv,
        )
        return np.moveaxis(out, 0, -1)

    return kernel


max_ = _make_minmax(np.maximum, True, skipna=False)
nanmax = _make_minmax(np.maximum, True, skipna=True)
min_ = _make_minmax(np.minimum, False, skipna=False)
nanmin = _make_minmax(np.minimum, False, skipna=True)


def nanlen(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    codes, data, valid = _prep(group_idx, array)
    mask = _nan_mask(data, kw.get("nat", False))
    if mask is None:
        out = np.bincount(codes[valid], minlength=size).astype(dtype or np.intp)
        out = np.broadcast_to(
            out.reshape((size,) + (1,) * (data.ndim - 1)), (size,) + data.shape[1:]
        ).copy()
    else:
        out = np.zeros((size,) + data.shape[1:], dtype=dtype or np.intp)
        np.add.at(out, codes[valid], mask[valid].astype(out.dtype))
    out = _apply_fill(out, codes, valid, size, fill_value, identity=0)
    return np.moveaxis(out, 0, -1)


def len_(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    codes, data, valid = _prep(group_idx, array)
    out = np.bincount(codes[valid], minlength=size).astype(dtype or np.intp)
    out = np.broadcast_to(
        out.reshape((size,) + (1,) * (data.ndim - 1)), (size,) + data.shape[1:]
    ).copy()
    return np.moveaxis(out, 0, -1)


def _mean_impl(group_idx, array, *, size, fill_value, dtype, skipna):
    codes, data, valid = _prep(group_idx, array)
    mask = _nan_mask(data) if skipna else None
    if dtype is None:
        dtype = np.result_type(data.dtype, np.float64) if data.dtype.kind in "iub" else data.dtype
    out_dtype = np.dtype(dtype)
    dtype = _acc_dtype(out_dtype)
    work = data if mask is None else np.where(mask, data, 0)
    total = _scatter(np.add, codes, work.astype(dtype, copy=False), valid, size, 0, dtype)
    if mask is None:
        cnt = np.bincount(codes[valid], minlength=size).astype(dtype)
        cnt = cnt.reshape((size,) + (1,) * (total.ndim - 1))
    else:
        cnt = np.zeros((size,) + data.shape[1:], dtype=dtype)
        np.add.at(cnt, codes[valid], mask[valid].astype(dtype))
    with np.errstate(invalid="ignore", divide="ignore"):
        out = total / cnt
    empty = np.broadcast_to(cnt, out.shape) == 0
    out = np.where(empty, np.nan if fill_value is None else fill_value, out)
    if out.dtype != out_dtype and out_dtype.kind == "f":
        out = out.astype(out_dtype)
    return np.moveaxis(out, 0, -1)


def mean(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _mean_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, skipna=False)


def nanmean(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _mean_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, skipna=True)


def _var_impl(group_idx, array, *, size, fill_value, dtype, ddof, skipna, take_sqrt):
    codes, data, valid = _prep(group_idx, array)
    mask = _nan_mask(data) if skipna else None
    if dtype is None:
        dtype = np.result_type(data.dtype, np.float64) if data.dtype.kind in "iub" else data.dtype
    out_dtype = np.dtype(dtype)
    dtype = _acc_dtype(out_dtype)
    work = (data if mask is None else np.where(mask, data, 0)).astype(dtype, copy=False)
    total = _scatter(np.add, codes, work, valid, size, 0, dtype)
    if mask is None:
        cnt1d = np.bincount(codes[valid], minlength=size).astype(dtype)
        cnt = cnt1d.reshape((size,) + (1,) * (total.ndim - 1))
    else:
        cnt = np.zeros((size,) + data.shape[1:], dtype=dtype)
        np.add.at(cnt, codes[valid], mask[valid].astype(dtype))
    with np.errstate(invalid="ignore", divide="ignore"):
        mean_g = total / np.where(cnt > 0, cnt, 1)
    dev = work - np.broadcast_to(mean_g, (size,) + data.shape[1:])[codes.clip(0, size - 1)]
    dev = np.where(valid.reshape((-1,) + (1,) * (dev.ndim - 1)), dev, 0)
    if mask is not None:
        dev = np.where(mask, dev, 0)
    m2 = _scatter(np.add, codes, dev * dev, valid, size, 0, dtype)
    denom = np.broadcast_to(cnt, m2.shape) - ddof
    with np.errstate(invalid="ignore", divide="ignore"):
        out = m2 / denom
    out = np.where(denom > 0, out, np.nan)
    if take_sqrt:
        out = np.sqrt(out)
    empty = np.broadcast_to(cnt, out.shape) == 0
    out = np.where(empty, np.nan if fill_value is None else fill_value, out)
    if out.dtype != out_dtype and out_dtype.kind == "f":
        out = out.astype(out_dtype)
    return np.moveaxis(out, 0, -1)


def var(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, ddof=0, **kw):
    return _var_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, ddof=ddof, skipna=False, take_sqrt=False)


def nanvar(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, ddof=0, **kw):
    return _var_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, ddof=ddof, skipna=True, take_sqrt=False)


def std(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, ddof=0, **kw):
    return _var_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, ddof=ddof, skipna=False, take_sqrt=True)


def nanstd(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, ddof=0, **kw):
    return _var_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, ddof=ddof, skipna=True, take_sqrt=True)


def var_chunk(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, skipna=True, **kw):
    from .multiarray import MultiArray

    codes, data, valid = _prep(group_idx, array)
    mask = _nan_mask(data) if skipna else None
    if dtype is None:
        dtype = np.result_type(data.dtype, np.float64) if data.dtype.kind in "iub" else data.dtype
    dtype = _acc_dtype(dtype)  # intermediates stay f32 (cast at finalize)
    work = (data if mask is None else np.where(mask, data, 0)).astype(dtype, copy=False)
    total = _scatter(np.add, codes, work, valid, size, 0, dtype)
    cnt = np.zeros((size,) + data.shape[1:], dtype=dtype)
    contrib = np.ones(data.shape, dtype=dtype) if mask is None else mask.astype(dtype)
    np.add.at(cnt, codes[valid], contrib[valid])
    with np.errstate(invalid="ignore", divide="ignore"):
        mean_g = total / np.where(cnt > 0, cnt, 1)
    mean_b = np.broadcast_to(mean_g, (size,) + data.shape[1:])
    dev = work - mean_b[codes.clip(0, size - 1)]
    dev = np.where(valid.reshape((-1,) + (1,) * (dev.ndim - 1)), dev, 0)
    if mask is not None:
        dev = np.where(mask, dev, 0)
    m2 = _scatter(np.add, codes, dev * dev, valid, size, 0, dtype)
    bshape = np.broadcast_shapes(total.shape, cnt.shape)
    return MultiArray(
        (
            np.moveaxis(np.broadcast_to(m2, bshape).copy(), 0, -1),
            np.moveaxis(np.broadcast_to(total, bshape).copy(), 0, -1),
            np.moveaxis(np.broadcast_to(cnt, bshape).copy(), 0, -1),
        )
    )


def all_(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    codes, data, valid = _prep(group_idx, array)
    out = np.ones((size,) + data.shape[1:], dtype=bool)
    np.logical_and.at(out, codes[valid], data[valid].astype(bool))
    out = _apply_fill(out, codes, valid, size, fill_value)
    return np.moveaxis(out, 0, -1)


def any_(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    codes, data, valid = _prep(group_idx, array)
    out = np.zeros((size,) + data.shape[1:], dtype=bool)
    np.logical_or.at(out, codes[valid], data[valid].astype(bool))
    out = _apply_fill(out, codes, valid, size, fill_value)
    return np.moveaxis(out, 0, -1)


def _arg_impl(group_idx, array, *, size, fill_value, skipna, arg_of_max, nat=False):
    codes, data, valid = _prep(group_idx, array)
    mask = _nan_mask(data, nat)
    if data.dtype.kind in "iub" and mask is not None:
        # nat ints (datetime64 viewed as int64): keep integer precision
        info = np.iinfo(data.dtype)
        lo, hi = info.min + 1, info.max
        key = data.copy()
        key[~mask] = (lo if arg_of_max else hi) if skipna else (hi if arg_of_max else lo)
        init = lo if arg_of_max else hi
    else:
        key = data.astype(np.float64, copy=True) if data.dtype.kind in "iub" else data.copy()
        if mask is not None:
            if skipna:
                key[~mask] = -np.inf if arg_of_max else np.inf
            else:
                key[~mask] = np.inf if arg_of_max else -np.inf
        init = -np.inf if arg_of_max else np.inf
    best = _scatter(np.maximum if arg_of_max else np.minimum, codes, key, valid, size, init)
    hit = key == best[codes.clip(0, size - 1)]
    n = data.shape[0]
    iota = np.broadcast_to(np.arange(n).reshape((n,) + (1,) * (data.ndim - 1)), data.shape)
    cand = np.where(hit, iota, n)
    if skipna and mask is not None:
        cand = np.where(mask, cand, n)
    pos = _scatter(np.minimum, codes, cand, valid, size, n)
    if not skipna and mask is not None:
        # numpy parity: any NaN (NaT) in the group short-circuits the value
        # race — the first missing position is the answer (even over ±inf)
        first_nan = _scatter(np.minimum, codes, np.where(mask, n, iota), valid, size, n)
        pos = np.where(first_nan < n, first_nan, pos)
    if skipna and mask is not None:
        cnt = np.zeros((size,) + data.shape[1:], dtype=np.intp)
        np.add.at(cnt, codes[valid], mask[valid].astype(np.intp))
        present = cnt > 0
    else:
        present = np.bincount(codes[valid], minlength=size) > 0
    fv = -1 if fill_value is None else fill_value
    present = np.broadcast_to(
        present.reshape(present.shape + (1,) * (pos.ndim - present.ndim)), pos.shape
    )
    out = np.where(present & (pos < n), pos, fv)
    return np.moveaxis(out, 0, -1)


def argmax(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _arg_impl(group_idx, array, size=size, fill_value=fill_value, skipna=False, arg_of_max=True, nat=kw.get("nat", False))


def argmin(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _arg_impl(group_idx, array, size=size, fill_value=fill_value, skipna=False, arg_of_max=False, nat=kw.get("nat", False))


def nanargmax(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _arg_impl(group_idx, array, size=size, fill_value=fill_value, skipna=True, arg_of_max=True, nat=kw.get("nat", False))


def nanargmin(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _arg_impl(group_idx, array, size=size, fill_value=fill_value, skipna=True, arg_of_max=False, nat=kw.get("nat", False))


def _firstlast_impl(group_idx, array, *, size, fill_value, skipna, last, nat=False):
    codes, data, valid = _prep(group_idx, array)
    mask = _nan_mask(data, nat) if skipna else None
    n = data.shape[0]
    iota = np.broadcast_to(np.arange(n).reshape((n,) + (1,) * (data.ndim - 1)), data.shape)
    if mask is not None:
        iota = np.where(mask, iota, -1 if last else n)
    pos = _scatter(np.maximum if last else np.minimum, codes, iota, valid, size, -1 if last else n)
    ok = (pos >= 0) & (pos < n)
    gathered = np.take_along_axis(data, pos.clip(0, n - 1), axis=0)
    is_inexact = np.issubdtype(data.dtype, np.floating) or np.issubdtype(
        data.dtype, np.complexfloating
    )
    fv = fill_value
    if fv is None:
        fv = np.nan if is_inexact else 0
    if _nanlike(fv) and not is_inexact:
        gathered = gathered.astype(np.float64)
    out = np.where(ok, gathered, fv)
    return np.moveaxis(out, 0, -1)


def first(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _firstlast_impl(group_idx, array, size=size, fill_value=fill_value, skipna=False, last=False, nat=kw.get("nat", False))


def last(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _firstlast_impl(group_idx, array, size=size, fill_value=fill_value, skipna=False, last=True, nat=kw.get("nat", False))


def nanfirst(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _firstlast_impl(group_idx, array, size=size, fill_value=fill_value, skipna=True, last=False, nat=kw.get("nat", False))


def nanlast(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _firstlast_impl(group_idx, array, size=size, fill_value=fill_value, skipna=True, last=True, nat=kw.get("nat", False))


def _orderstat_loop(group_idx, array, *, size, fill_value, func):
    """Per-group python loop for order statistics; the numpy engine trades
    speed for simplicity here (the jax engine is the fast path)."""
    codes, data, valid = _prep(group_idx, array)  # (N, ...)
    first_shape = data.shape[1:]
    out = None
    for g in range(size):
        sel = (codes == g) & valid
        grp = data[sel]  # (k, ...)
        res = func(grp)
        if out is None:
            out = np.full((size,) + np.shape(res), fill_value if fill_value is not None else np.nan, dtype=np.result_type(np.float64, data.dtype))
        if grp.shape[0] == 0:
            continue  # leave the fill for empty groups
        out[g] = res
    if out is None:
        out = np.full((size,) + first_shape, fill_value if fill_value is not None else np.nan)
    return np.moveaxis(out, 0, -1)


def _quantile_impl(group_idx, array, *, size, fill_value, q, skipna, method="linear"):
    qs = np.atleast_1d(q)
    qfunc = np.nanquantile if skipna else np.quantile

    def per_group(grp):
        if grp.shape[0] == 0 or (skipna and np.all(np.isnan(grp))):
            return np.full((len(qs),) + grp.shape[1:], np.nan)
        with np.testing.suppress_warnings() as sup:
            sup.filter(RuntimeWarning)
            return qfunc(grp, qs, axis=0, method=method)

    out = _orderstat_loop(group_idx, array, size=size, fill_value=fill_value, func=per_group)
    # out: (..., nq at axis -2? ) — per_group returns (nq, cols...), loop stacks
    # to (size, nq, cols...) then moveaxis -> (nq, cols..., size)? Normalize:
    # _orderstat_loop gives (nq, cols..., size) after moveaxis of axis0.
    if np.ndim(q) == 0:
        out = out[0] if out.shape[0] == 1 else np.squeeze(out, axis=0)
    return out


def quantile(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, q, method="linear", **kw):
    return _quantile_impl(group_idx, array, size=size, fill_value=fill_value, q=q, skipna=False, method=method)


def nanquantile(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, q, method="linear", **kw):
    return _quantile_impl(group_idx, array, size=size, fill_value=fill_value, q=q, skipna=True, method=method)


def median(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _quantile_impl(group_idx, array, size=size, fill_value=fill_value, q=0.5, skipna=False)


def nanmedian(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _quantile_impl(group_idx, array, size=size, fill_value=fill_value, q=0.5, skipna=True)


def _mode_impl(group_idx, array, *, size, fill_value, skipna):
    def per_group(grp):
        if grp.shape[0] == 0:
            return np.full(grp.shape[1:], np.nan)
        out = np.empty(grp.shape[1:])
        flat = grp.reshape(grp.shape[0], -1)
        res = []
        for col in flat.T:
            c = col
            if skipna:
                c = c[~np.isnan(c)] if np.issubdtype(c.dtype, np.floating) else c
            if c.size == 0:
                res.append(np.nan)
                continue
            # scipy.stats.mode "propagate" (scipy >= 1.11): NaNs count as ONE
            # candidate value with their multiplicity — np.unique's equal_nan
            # collapse delivers exactly that; skipna dropped them above
            vals, cnts = np.unique(c, return_counts=True)
            res.append(vals[np.argmax(cnts)])
        return np.array(res).reshape(grp.shape[1:])

    return _orderstat_loop(group_idx, array, size=size, fill_value=fill_value, func=per_group)


def mode(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _mode_impl(group_idx, array, size=size, fill_value=fill_value, skipna=False)


def nanmode(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _mode_impl(group_idx, array, size=size, fill_value=fill_value, skipna=True)


def _sum_of_squares(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, skipna=False, **kw):
    arr = np.asarray(array)
    fn = nansum if skipna else sum_
    return fn(group_idx, arr * arr, axis=axis, size=size, fill_value=fill_value, dtype=dtype)


def sum_of_squares(group_idx, array, **kw):
    return _sum_of_squares(group_idx, array, skipna=False, **kw)


def nansum_of_squares(group_idx, array, **kw):
    return _sum_of_squares(group_idx, array, skipna=True, **kw)


def _grouped_scan_host(group_idx, array, kind, dtype=None, nat=False):
    """Host grouped scans via stable argsort (mirrors the jax engine shape).

    ``nat``: data is int64-viewed datetimes/timedeltas with missing =
    INT64_MIN; ffill/bfill fill from the last valid and leave NaT where
    nothing precedes, cumsum poisons the rest of the segment after a NaT
    (numpy's NaT + x = NaT), nancumsum skips NaT.
    """
    codes = np.asarray(group_idx).reshape(-1)
    data = np.moveaxis(np.asarray(array), -1, 0)
    if dtype is not None:
        data = data.astype(dtype, copy=False)
    out_dtype = data.dtype
    if kind in ("cumsum", "nancumsum") and not nat:
        data = data.astype(_acc_dtype(out_dtype), copy=False)  # f16 running sums saturate
    perm = np.argsort(codes, kind="stable")
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    sc = codes[perm]
    sd = np.take(data, perm, axis=0)
    boundaries = np.flatnonzero(np.r_[True, sc[1:] != sc[:-1]])
    out = np.empty_like(sd)
    for b, e in zip(boundaries, np.r_[boundaries[1:], len(sc)]):
        seg = sd[b:e]
        if kind in ("cumsum", "nancumsum"):
            if nat:
                miss = seg == _NAT_INT
                cs = np.where(miss, 0, seg).cumsum(axis=0)
                if kind == "cumsum":
                    cs = np.where(np.maximum.accumulate(miss, axis=0), _NAT_INT, cs)
                out[b:e] = cs
            elif kind == "cumsum":
                out[b:e] = np.cumsum(seg, axis=0)
            else:
                out[b:e] = np.nancumsum(seg, axis=0)
        elif kind in ("ffill", "bfill"):
            s = seg if kind == "ffill" else seg[::-1]
            isfloat = np.issubdtype(s.dtype, np.floating)
            if isfloat or nat:
                valid = (s != _NAT_INT) if nat else ~np.isnan(s)
                missing_val = _NAT_INT if nat else np.nan
                idx = np.where(valid, np.arange(s.shape[0]).reshape((-1,) + (1,) * (s.ndim - 1)), -1)
                np.maximum.accumulate(idx, axis=0, out=idx)
                filled = np.where(idx >= 0, np.take_along_axis(s, idx.clip(0), axis=0), missing_val)
            else:
                filled = s
            out[b:e] = filled if kind == "ffill" else filled[::-1]
    if out.dtype != out_dtype:
        out = out.astype(out_dtype)
    return np.moveaxis(np.take(out, inv, axis=0), 0, -1)


def cumsum(group_idx, array, *, axis=-1, size=None, fill_value=None, dtype=None, **kw):
    return _grouped_scan_host(group_idx, array, "cumsum", dtype=dtype, nat=kw.get("nat", False))


def nancumsum(group_idx, array, *, axis=-1, size=None, fill_value=None, dtype=None, **kw):
    return _grouped_scan_host(group_idx, array, "nancumsum", dtype=dtype, nat=kw.get("nat", False))


def ffill(group_idx, array, *, axis=-1, size=None, fill_value=None, dtype=None, **kw):
    return _grouped_scan_host(group_idx, array, "ffill", nat=kw.get("nat", False))


def bfill(group_idx, array, *, axis=-1, size=None, fill_value=None, dtype=None, **kw):
    return _grouped_scan_host(group_idx, array, "bfill", nat=kw.get("nat", False))


KERNELS = {
    "sum": sum_,
    "nansum": nansum,
    "prod": prod,
    "nanprod": nanprod,
    "max": max_,
    "nanmax": nanmax,
    "min": min_,
    "nanmin": nanmin,
    "mean": mean,
    "nanmean": nanmean,
    "var": var,
    "nanvar": nanvar,
    "std": std,
    "nanstd": nanstd,
    "var_chunk": var_chunk,
    "count": nanlen,
    "nanlen": nanlen,
    "len": len_,
    "all": all_,
    "any": any_,
    "argmax": argmax,
    "argmin": argmin,
    "nanargmax": nanargmax,
    "nanargmin": nanargmin,
    "first": first,
    "last": last,
    "nanfirst": nanfirst,
    "nanlast": nanlast,
    "median": median,
    "nanmedian": nanmedian,
    "quantile": quantile,
    "nanquantile": nanquantile,
    "mode": mode,
    "nanmode": nanmode,
    "sum_of_squares": sum_of_squares,
    "nansum_of_squares": nansum_of_squares,
    "cumsum": cumsum,
    "nancumsum": nancumsum,
    "ffill": ffill,
    "bfill": bfill,
}


def generic_kernel(func: str, group_idx, array, **kwargs):
    try:
        fn = KERNELS[func]
    except KeyError:
        raise NotImplementedError(f"numpy engine has no kernel for {func!r}") from None
    return fn(group_idx, array, **kwargs)
