"""Mesh construction helpers.

The reference's analogue of "pick a scheduler" (dask cluster / cubed spec) is
picking a device mesh. One logical axis is enough for groupby map-reduce —
the reduced axis is sharded over it; ICI carries the combine collectives.
Multi-host meshes work unchanged: jax.devices() spans hosts under
jax.distributed, and the same psum rides ICI within a host and DCN across.
"""

from __future__ import annotations

import numpy as np


def make_mesh(n_devices: int | None = None, axis_name: str = "data"):
    """A 1-D mesh over the first ``n_devices`` devices (default: all)."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices > len(devices):
        raise ValueError(f"Requested {n_devices} devices; only {len(devices)} available.")
    return Mesh(np.asarray(devices[:n_devices]), (axis_name,))
