"""Mesh construction helpers.

The reference's analogue of "pick a scheduler" (dask cluster / cubed spec) is
picking a device mesh. One logical axis is enough for groupby map-reduce —
the reduced axis is sharded over it; ICI carries the combine collectives.
Multi-host meshes work unchanged: jax.devices() spans hosts under
jax.distributed, and the same psum rides ICI within a host and DCN across.
"""

from __future__ import annotations

import numpy as np


def make_mesh(n_devices: int | None = None, axis_name: str = "data", *, shape=None, axis_names=None):
    """A 1-D mesh over the first ``n_devices`` devices (default: all), or a
    multi-axis mesh via ``shape``/``axis_names`` — e.g.
    ``make_mesh(shape=(n_hosts, 8), axis_names=("dcn", "ici"))`` for
    multi-host: the reduction axis is then sharded over BOTH axes
    (pass ``axis_name=("dcn", "ici")`` to groupby_reduce) and psum rides ICI
    within a host and DCN across.
    """
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if shape is not None:
        if axis_names is None or len(axis_names) != len(shape):
            raise ValueError("axis_names must match shape")
        need = int(np.prod(shape))
        if need > len(devices):
            raise ValueError(f"Requested {need} devices; only {len(devices)} available.")
        return Mesh(np.asarray(devices[:need]).reshape(shape), tuple(axis_names))
    if n_devices is None:
        n_devices = len(devices)
    if n_devices > len(devices):
        raise ValueError(f"Requested {n_devices} devices; only {len(devices)} available.")
    return Mesh(np.asarray(devices[:n_devices]), (axis_name,))
