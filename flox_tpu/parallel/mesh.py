"""Mesh construction helpers.

The reference's analogue of "pick a scheduler" (dask cluster / cubed spec) is
picking a device mesh. One logical axis is enough for groupby map-reduce —
the reduced axis is sharded over it; ICI carries the combine collectives.
Multi-host meshes work unchanged: jax.devices() spans hosts under
jax.distributed, and the same psum rides ICI within a host and DCN across.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np


def shard_map(
    f: Callable, *, mesh: Any, in_specs: Any, out_specs: Any, check_vma: bool = True
) -> Callable:
    """Version-compat wrapper over ``jax.shard_map``.

    ``jax.shard_map`` only exists as a top-level API in newer jax; older
    releases ship it as ``jax.experimental.shard_map.shard_map`` with the
    replication-check keyword spelled ``check_rep`` instead of ``check_vma``.
    Every shard_map construction in the package goes through here (floxlint
    FLX004 flags bare ``jax.shard_map`` attribute access) so the fallback and
    the keyword translation live in exactly one place.
    """
    import inspect

    import jax

    native = getattr(jax, "shard_map", None)  # floxlint: disable=FLX004
    if native is not None:
        # transitional releases expose jax.shard_map but still spell the
        # replication-check kwarg check_rep; probe the signature rather than
        # retrying on TypeError (which would mask real construction errors)
        try:
            params = inspect.signature(native).parameters
        except (TypeError, ValueError):
            params = {}
        kwarg = "check_vma" if "check_vma" in params or not params else "check_rep"
        return native(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **{kwarg: check_vma}
        )
    from jax.experimental.shard_map import (  # floxlint: disable=FLX004
        shard_map as experimental_shard_map,
    )

    return experimental_shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )


def axis_size(axis_name: str) -> int:
    """Version-compat ``jax.lax.axis_size``: newer jax has it as an API;
    older releases get it via the constant-folding idiom ``psum(1, axis)``,
    which resolves to a static int at trace time. FLX004 flags bare
    ``jax.lax.axis_size`` access so the fallback lives here only."""
    import jax

    fn = getattr(jax.lax, "axis_size", None)  # floxlint: disable=FLX004
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def make_mesh(
    n_devices: int | None = None,
    axis_name: str = "data",
    *,
    shape: tuple[int, ...] | None = None,
    axis_names: tuple[str, ...] | None = None,
) -> Any:
    """A 1-D mesh over the first ``n_devices`` devices (default: all), or a
    multi-axis mesh via ``shape``/``axis_names`` — e.g.
    ``make_mesh(shape=(n_hosts, 8), axis_names=("dcn", "ici"))`` for
    multi-host: the reduction axis is then sharded over BOTH axes
    (pass ``axis_name=("dcn", "ici")`` to groupby_reduce) and psum rides ICI
    within a host and DCN across.
    """
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if shape is not None:
        if axis_names is None or len(axis_names) != len(shape):
            raise ValueError("axis_names must match shape")
        need = int(np.prod(shape))
        if need > len(devices):
            raise ValueError(f"Requested {need} devices; only {len(devices)} available.")
        return Mesh(np.asarray(devices[:need]).reshape(shape), tuple(axis_names))
    if n_devices is None:
        n_devices = len(devices)
    if n_devices > len(devices):
        raise ValueError(f"Requested {n_devices} devices; only {len(devices)} available.")
    return Mesh(np.asarray(devices[:n_devices]), (axis_name,))
