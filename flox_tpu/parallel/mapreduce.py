"""Sharded groupby reductions: one SPMD program per aggregation (L5).

The reference's three dask execution methods (core.py:89, dask.py:325-573)
map onto mesh programs as follows:

* ``map-reduce``: shard-local ``chunk_reduce`` producing dense (size,)
  intermediates, then XLA collectives as the tree combine — ``psum`` for
  additive intermediates (the reference's ``_simple_combine``,
  dask.py:90-144), ``pmax``/``pmin`` for extrema, a two-phase psum for the
  variance triple (the collective form of the Chan merge the reference does
  pairwise in ``_var_combine``, aggregations.py:392-451), and
  all_gather+fold for order-dependent tails (first/last/prod — the
  reference's ``_grouped_combine`` cases, dask.py:233-317).
* ``cohorts``: ``psum_scatter`` distributes *group ownership* — each device
  combines and finalizes ``size/ndev`` groups, then the result is
  all-gathered. Communication drops from O(size × ndev) to O(size), the
  same economics that motivate the reference's cohort graph surgery
  (cohorts.py:109-301) — but as a single collective, not N subgraphs.
* ``blockwise``: no combine at all — valid when each group's members are
  entirely within one shard (after rechunk.reshard_for_blockwise); each shard
  finalizes its own groups and owners are selected by nonzero counts
  (parity: dask.py:520-541). This is also how order statistics
  (median/quantile/mode) run on a mesh, since they need whole groups.

Everything here is traced under one ``jax.jit``: factorized codes go in,
the finalized dense result comes out, and XLA overlaps the per-shard
reduction with the collectives.
"""

from __future__ import annotations

import functools
from time import perf_counter
from typing import Any

import numpy as np

from .. import utils
from ..aggregations import Aggregation
from ..cache import LRUCache
from ..multiarray import MultiArray
from .mesh import axis_size, make_mesh, shard_map

_BIG = np.iinfo(np.int32).max


# ---------------------------------------------------------------------------
# local building blocks (traced inside shard_map)
# ---------------------------------------------------------------------------


def _local_chunk(agg: Aggregation, codes_sh, arr_sh, size: int, nat: bool):
    """Run the agg's chunk kernels on this shard -> list of intermediates.

    Chunk entries may be kernel names or user callables with the plugin
    signature ``f(group_idx, array, *, axis, size, fill_value, dtype, **kw)``
    (the reference's custom-Aggregation contract, aggregations.py:161-301).
    """
    from ..aggregations import FusedAggregation, fused_chunk_stats
    from ..kernels import generic_kernel

    if isinstance(agg, FusedAggregation):
        # the multi-statistic plan has its own executor: deduplicated legs,
        # megakernel-eligible subsets collapsed into one Pallas pass
        return fused_chunk_stats(agg, codes_sh, arr_sh, size=size, engine="jax")

    inters = []
    fills = agg.fill_value.get("intermediate", ())
    for entry, fv in zip(agg.chunk, list(fills) + [None] * len(agg.chunk)):
        if isinstance(entry, tuple):
            name, extra = entry[0], dict(entry[1])
        else:
            name, extra = entry, {}
        if nat:
            extra["nat"] = True
        if callable(name):
            inters.append(name(codes_sh, arr_sh, size=size, fill_value=fv, **extra))
            continue
        if name in ("sum", "nansum", "prod", "nanprod", "sum_of_squares", "nansum_of_squares"):
            # bf16/f16 intermediates must travel and psum in the f32
            # accumulator; the cast back to the final dtype happens once,
            # at finalize (kernels._acc_dtype)
            extra["keep_acc"] = True
        extra.update(agg.finalize_kwargs if name.startswith("var_chunk") else {})
        inters.append(
            generic_kernel(name, codes_sh, arr_sh, size=size, fill_value=fv, **extra)
        )
    return inters


def _local_counts(codes_sh, arr_sh, size: int, skipna: bool, nat: bool):
    from ..kernels import generic_kernel

    func = "nanlen" if skipna else "len"
    kw = {"nat": True} if nat else {}
    return generic_kernel(func, codes_sh, arr_sh, size=size, **kw)


def _local_firstlast(codes_sh, arr_sh, size: int, *, skipna: bool, last: bool, nat: bool, offset):
    """(value, global position) per group for the first/last combine."""
    import jax
    import jax.numpy as jnp

    from ..kernels import _from_leading, _iota_like, _nan_mask, _safe_codes, _seg, _to_leading

    codes = _safe_codes(codes_sh, size)
    data = _to_leading(arr_sh)
    mask = _nan_mask(data, nat) if skipna else None
    iota = _iota_like(data) + offset  # global positions
    if mask is not None:
        iota = jnp.where(mask, iota, -1 if last else _BIG)
    pos = _seg("max" if last else "min", iota, codes, size)
    ok = (pos >= 0) & (pos < _BIG)
    local_idx = jnp.clip(pos - offset, 0, data.shape[0] - 1)
    val = jnp.take_along_axis(data, local_idx, axis=0)
    # positions from other shards will be resolved by the combine; mark
    # invalid local picks so they lose
    pos = jnp.where(ok, pos, -1 if last else _BIG)
    return _from_leading(val), _from_leading(pos)


# ---------------------------------------------------------------------------
# combines (collectives)
# ---------------------------------------------------------------------------


def _combine_simple(op: str, x, axis_name: str, nat: bool = False):
    import jax
    import jax.numpy as jnp

    if op == "sum":
        return jax.lax.psum(x, axis_name)
    if op in ("max", "min"):
        out = jax.lax.pmax(x, axis_name) if op == "max" else jax.lax.pmin(x, axis_name)
        # XLA's all-reduce max/min DROPS NaN; numpy's min/max propagate it.
        # Re-inject the missing marker where any shard's intermediate had it
        # (NaN for floats, INT64_MIN==NaT for datetime views).
        if jnp.issubdtype(x.dtype, jnp.floating):
            has_nan = jax.lax.psum(jnp.isnan(x).astype(jnp.int32), axis_name) > 0
            out = jnp.where(has_nan, jnp.asarray(jnp.nan, out.dtype), out)
        elif nat and jnp.issubdtype(x.dtype, jnp.signedinteger):
            marker = jnp.asarray(np.iinfo(np.int64).min, dtype=x.dtype)
            has_nat = jax.lax.psum((x == marker).astype(jnp.int32), axis_name) > 0
            out = jnp.where(has_nat, marker, out)
        return out
    if op == "prod":
        gathered = jax.lax.all_gather(x, axis_name)  # (ndev, size, ...)
        return gathered.prod(axis=0)
    raise ValueError(f"Unknown combine op {op!r}")


def _combine_var(ma: MultiArray, axis_name: str):
    """Collective Chan merge: two psums instead of pairwise host folds."""
    import jax
    import jax.numpy as jnp

    m2, total, n = ma.arrays
    big_n = jax.lax.psum(n, axis_name)
    big_t = jax.lax.psum(total, axis_name)
    mu = big_t / jnp.where(big_n > 0, big_n, 1)
    mu_d = total / jnp.where(n > 0, n, 1)
    adj = n * (mu_d - mu) ** 2
    big_m2 = jax.lax.psum(m2 + adj, axis_name)
    return MultiArray((big_m2, big_t, big_n))


def _combine_arg(val, idx, axis_name: str, arg_of_max: bool, nat: bool = False):
    import jax
    import jax.numpy as jnp

    gv = _combine_simple("max" if arg_of_max else "min", val, axis_name, nat=nat)
    hit = val == gv
    if jnp.issubdtype(val.dtype, jnp.floating):
        # NaN-propagating argreductions: the winning value may be NaN, and
        # NaN != NaN — shards whose extreme is NaN must still contend
        hit = hit | (jnp.isnan(val) & jnp.isnan(gv))
    cand = jnp.where(hit & (idx >= 0), idx, _BIG)
    gidx = jax.lax.pmin(cand, axis_name)
    return gv, jnp.where(gidx < _BIG, gidx, -1)


def _combine_firstlast(val, pos, axis_name: str, last: bool):
    import jax
    import jax.numpy as jnp

    vals = jax.lax.all_gather(val, axis_name)  # (ndev, ..., size)
    poss = jax.lax.all_gather(pos, axis_name)
    pick = jnp.argmax(poss, axis=0) if last else jnp.argmin(poss, axis=0)
    val_g = jnp.take_along_axis(vals, pick[None], axis=0)[0]
    pos_g = jnp.take_along_axis(poss, pick[None], axis=0)[0]
    ok = (pos_g >= 0) & (pos_g < _BIG)
    return val_g, ok


def _combine_intermediates(agg: Aggregation, inters, axis_name, nat: bool):
    """Cross-shard combine of dense per-shard intermediates.

    ``inters``: [val, global_idx] for argreductions, [val, pos] for
    first/last, else one entry per ``agg.combine`` op. The ONE place the
    combine contract lives — shared by the map-reduce program and the
    streaming mesh runtime's final combine (streaming.py), so the NaT
    re-injection rule, the user-fold gather shape, and the Chan merge
    cannot drift between the two.
    """
    import jax

    skipna = agg.name.startswith("nan") or agg.name == "count"
    nat_markers = nat and not skipna
    if agg.reduction_type == "argreduce":
        gv, garg = _combine_arg(
            inters[0], inters[1], axis_name,
            arg_of_max="max" in str(agg.chunk[1]), nat=nat_markers,
        )
        return [gv, garg]
    if agg.combine in (("first",), ("last",)):
        val_g, _ok = _combine_firstlast(
            inters[0], inters[1], axis_name, last=agg.combine == ("last",)
        )
        return [val_g]
    combined = []
    for inter, op in zip(inters, agg.combine):
        if op == "var":
            combined.append(_combine_var(inter, axis_name))
        elif callable(op):
            # general combine for user Aggregations (the reference's
            # _grouped_combine role, dask.py:233-317): gather every
            # shard's dense intermediate and hand the stack to the user
            # fold — contract: op(stacked) with stacked (ndev, ..., size)
            # -> (..., size). Leaf-wise over MultiArray pytrees.
            if isinstance(inter, MultiArray):
                gathered = MultiArray(
                    tuple(jax.lax.all_gather(a, axis_name) for a in inter.arrays)
                )
            else:
                gathered = jax.lax.all_gather(inter, axis_name)
            combined.append(op(gathered))
        else:
            combined.append(_combine_simple(op, inter, axis_name, nat=nat_markers))
    return combined


def _finalize_combined(agg: Aggregation, combined, counts):
    """Pick/fold the combined intermediates into the result and apply the
    final fill — shared by every mesh program and the streaming runtime."""
    from ..aggregations import FusedAggregation

    if isinstance(agg, FusedAggregation):
        # multi-output: one tuple entry per requested statistic, each with
        # its own presence/fill semantics (the generic counts channel is
        # advisory here — every slot reads its own presence leg)
        return agg.finalize_fused(combined, counts)
    if agg.reduction_type == "argreduce":
        result = combined[1]
    elif agg.finalize is not None:
        result = agg.finalize(*combined, **agg.finalize_kwargs)
    else:
        result = combined[0]
    return _apply_final_fill(result, counts, agg)


# ---------------------------------------------------------------------------
# the SPMD program
# ---------------------------------------------------------------------------


def _pad_to(n: int, multiple: int) -> int:
    return (-n) % multiple


def _norm_axes(axis_name, mesh=None) -> tuple[str, ...]:
    """Accept a single mesh axis name or a tuple (e.g. ("dcn", "ici"));
    validates against the mesh when given."""
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    if mesh is not None:
        missing = [a for a in axes if a not in mesh.shape]
        if missing:
            raise ValueError(f"mesh has no axes {missing}; mesh axes: {tuple(mesh.shape)}")
    return axes


def _flat_axis_index(axes: tuple[str, ...]):
    """Flattened device index across mesh axes, major-to-minor — matches the
    order PartitionSpec((a0, a1)) shards the data axis."""
    import jax

    idx = jax.lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * axis_size(a) + jax.lax.axis_index(a)
    return idx


@functools.lru_cache(maxsize=256)
def _cached_mesh_default():
    return make_mesh()


# order statistics whose device kernel can distribute by psum-ing the
# radix-select counting passes (kernels._radix_select axis_name=). mode is
# NOT here: its run-length structure needs contiguous sorted groups.
_DISTRIBUTED_ORDER_STATS = ("median", "nanmedian", "quantile", "nanquantile")


def _is_additive(agg: Aggregation) -> bool:
    """Combines expressible as psum / psum_scatter (the ops the cohorts and
    blocked programs can distribute by group ownership)."""
    return agg.reduction_type != "argreduce" and bool(agg.combine) and all(
        op in ("sum", "var") for op in agg.combine
    )


from ..utils import fmt_bytes  # noqa: E402 — guard-message formatting


def _est_itemsize(dtype) -> int:
    """Accumulator width for the footprint estimate: intermediates travel in
    >= f32 accumulators; complex dtypes keep their full 2x width."""
    return max(4, np.dtype(str(dtype)).itemsize)


def dense_intermediate_bytes(
    lead_elems: int, size: int, dtype, agg: Aggregation, ndev: int
) -> int:
    """Per-device HBM estimate for the dense (..., size) intermediates a
    map-reduce program materializes (VERDICT r3 #6). Counts one buffer per
    chunk leg plus the counts leg; legs whose combine all_gathers (callable
    folds, prod, first/last) cost ndev x their dense size."""
    itemsize = _est_itemsize(dtype)
    per_leg = lead_elems * size * itemsize
    legs = 1  # counts
    # blockwise-only aggs (order statistics) have no chunk/combine legs:
    # one result buffer next to the counts
    ops = agg.combine or ("sum",) * max(1, len(agg.chunk or ()) or 1)
    if agg.combine in (("first",), ("last",)) or agg.reduction_type == "argreduce":
        legs += 2  # (value, position) pair, pmax/pmin combine
        if agg.combine in (("first",), ("last",)):
            legs += 2 * (ndev - 1)  # the pair is all_gathered
        return per_leg * legs
    for op in ops:
        if op == "var":
            legs += 3  # the Chan triple psums leaf-wise
        elif op == "sum" or op in ("max", "min"):
            legs += 1
        else:  # callable user folds and prod travel via all_gather
            legs += ndev
    return per_leg * legs


def sharded_groupby_reduce(
    array: Any,
    codes: Any,
    agg: Aggregation,
    *,
    size: int,
    mesh: Any = None,
    axis_name: str | tuple[str, ...] = "data",
    method: str = "map-reduce",
    nat: bool = False,
) -> Any:
    """Run one grouped reduction as a sharded SPMD program.

    ``array``: (..., N) (host or device), sharded over the trailing axis;
    ``codes``: (N,) int64 with -1 = missing. Returns the finalized dense
    result, replicated: shape (*new_dims, ..., size).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        mesh = _cached_mesh_default()
    axes = _norm_axes(axis_name, mesh)
    ndev = int(np.prod([mesh.shape[a] for a in axes]))

    if agg.blockwise_only and method != "blockwise":
        if agg.name in _DISTRIBUTED_ORDER_STATS:
            # quantile/median DO run distributed here — the radix-select
            # bisection's counting passes psum across shards, so no shard
            # ever needs a whole group (kernels._radix_select). The
            # reference must force blockwise for order statistics
            # (core.py:685-709); this framework does not.
            if method == "cohorts":
                import warnings

                # the caller asked for cohorts BY NAME and is getting a
                # different execution method — that reroute must be
                # visible to them, not buried in a debug log (ADVICE r5)
                warnings.warn(
                    f"method='cohorts' has no ownership win for order "
                    f"statistics; {agg.name!r} runs the distributed "
                    "radix-select 'map-reduce' program instead",
                    UserWarning,
                    stacklevel=2,
                )
            method = "map-reduce"
        else:
            raise NotImplementedError(
                f"{agg.name!r} needs whole groups on one shard; use method='blockwise' "
                "with shard-local groups (rechunk.reshard_for_blockwise prepares that "
                "layout — the reference forces blockwise for these too, core.py:685-709)."
            )

    if agg.appended_count:
        # the mesh programs compute counts themselves; the appended nanlen
        # would otherwise leak into agg.finalize as a stray positional arg
        import copy as _copy

        agg = _copy.deepcopy(agg)
        agg.chunk = agg.chunk[:-1]
        agg.combine = agg.combine[:-1]
        agg.fill_value["intermediate"] = agg.fill_value["intermediate"][:-1]
        agg.appended_count = False

    if nat:
        from ..aggregations import shift_nat_identity_fills

        shift_nat_identity_fills(agg)

    # -- huge-label-space routing (VERDICT r3 #6) --------------------------
    # Estimate the dense per-device intermediate footprint; above the
    # ceiling, additive aggs run the blocked program (every intermediate is
    # (..., size/ndev) from the start, one psum per owner block) and
    # non-additive ones fail actionably instead of OOMing HBM.
    from ..options import OPTIONS

    arr_probe = array if hasattr(array, "shape") else np.asarray(array)
    lead_elems = int(np.prod(arr_probe.shape[:-1])) if arr_probe.ndim > 1 else 1
    est = dense_intermediate_bytes(lead_elems, size, arr_probe.dtype, agg, ndev)
    ceiling = OPTIONS["dense_intermediate_bytes_max"]
    blocked = False
    if est > ceiling and method in ("map-reduce", "cohorts"):
        # blocked peak per device: the replicated dense result (irreducible
        # — the output contract is a full (..., size) array) plus the
        # per-owner-block intermediates, est/ndev. If even that exceeds the
        # ceiling (ndev too small, or the result alone is too big), blocking
        # would proceed straight into the OOM it exists to prevent — raise.
        result_bytes = lead_elems * size * _est_itemsize(arr_probe.dtype)
        blocked_est = result_bytes + est // ndev
        if _is_additive(agg) and blocked_est <= ceiling:
            blocked = True
            method = "cohorts"  # blocked execution lives in the cohorts program
            import logging

            logging.getLogger("flox_tpu.parallel.mapreduce").debug(
                "dense intermediates ~%s exceed dense_intermediate_bytes_max"
                " (%s): using the blocked owner-by-owner program",
                fmt_bytes(est), fmt_bytes(ceiling),
            )
        else:
            how = (
                "its combine cannot be distributed by group ownership"
                if not _is_additive(agg)
                else f"even the blocked owner-by-owner program needs "
                f"~{fmt_bytes(blocked_est)}/device over {ndev} device(s)"
            )
            raise ValueError(
                f"{agg.name!r} over {size} groups needs ~{fmt_bytes(est)} of "
                f"dense (..., size) intermediates per device, above the "
                f"{fmt_bytes(ceiling)} dense_intermediate_bytes_max ceiling, "
                f"and {how}. Options: use engine='sort' "
                "(FLOX_TPU_DEFAULT_ENGINE=sort — intermediates and collectives "
                "then cover only the groups actually present); reduce "
                "expected_groups; shard over more devices; use "
                "method='blockwise' after rechunk.reshard_for_blockwise (whole "
                "groups per shard, no dense combine); or raise "
                "set_options(dense_intermediate_bytes_max=...) if the device "
                "really has the headroom."
            )

    cohort_perm = None
    if method == "cohorts" and not blocked:
        # align psum_scatter ownership tiles with detected cohorts (memoized
        # detection — the auto-method path already ran it on these codes).
        # Blocked runs skip detection: at the group counts that trigger
        # blocking, the host-side bitmask/containment analysis costs more
        # than the locality it buys, and block ownership is already uniform.
        from ..cohorts import chunks_from_shards, find_group_cohorts, ownership_permutation

        codes_np = np.asarray(codes).reshape(-1)
        _, mapping = find_group_cohorts(
            codes_np, chunks_from_shards(codes_np.shape[0], ndev),
            expected_groups=range(size),
        )
        cohort_perm = ownership_permutation(mapping, size, ndev)

    arr = utils.asarray_device(array)
    if utils.is_jax_array(codes):
        # pre-staged device codes (a registry put / factorize.Prefactorized
        # feeds its per-shard codes straight in): skip the host round-trip —
        # the put already paid the one H2D
        codes_dev = codes if codes.dtype == jnp.int32 else codes.astype(jnp.int32)
    else:
        codes_dev = jnp.asarray(np.asarray(codes), dtype=jnp.int32)
    n = codes_dev.shape[0]
    pad = _pad_to(n, ndev)
    if pad:
        codes_dev = jnp.concatenate([codes_dev, jnp.full((pad,), -1, dtype=jnp.int32)])
        widths = [(0, 0)] * (arr.ndim - 1) + [(0, pad)]
        arr = jnp.pad(arr, widths)
    shard_len = codes_dev.shape[0] // ndev

    # pad the group axis for psum_scatter ownership slicing
    size_pad = size + _pad_to(n=size, multiple=ndev) if method == "cohorts" else size

    spec_entry = axes if len(axes) > 1 else axes[0]
    in_specs = (
        P(*([None] * (arr.ndim - 1) + [spec_entry])),
        P(spec_entry),
    )
    out_specs = P()  # replicated

    from ..options import trace_fingerprint

    cache_key = (
        _agg_cache_key(agg), size, size_pad, method, axes, shard_len, nat,
        mesh, arr.ndim, blocked, trace_fingerprint(),
        None if cohort_perm is None else cohort_perm.tobytes(),
    )
    from .. import telemetry

    tm_on = telemetry.enabled()
    if tm_on:
        # cost-ledger baseline: dispatch wall + the jax-compile delta this
        # mesh dispatch provokes (the build path's first run pays the
        # trace+compile; the hit path should read ~0 compiles)
        compiles0 = telemetry.METRICS.get("jax.compiles")
        compile_ms0 = telemetry.METRICS.get("jax.compile_ms")
        t_dispatch0 = perf_counter()

    fn = _PROGRAM_CACHE.get(cache_key)
    if fn is None:
        telemetry.count("cache.program_misses")
        program = _build_program(
            agg, size=size, size_pad=size_pad, method=method, axis_name=axes,
            shard_len=shard_len, nat=nat, cohort_perm=cohort_perm,
            blocked=blocked, ndev=ndev,
        )
        # check_vma=False: outputs are replicated by construction (psum /
        # all_gather), but the static checker cannot infer that through
        # argmin/take_along_axis owner-selection.
        fn = jax.jit(
            shard_map(
                program, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
            )
        )
        # bounded LRU: a cold key past capacity evicts ONE stale program
        # (counted in cache.stats()["evictions"]), never the whole hot set
        _PROGRAM_CACHE[cache_key] = fn
        from ..profiling import timed

        # jit/shard_map construction is lazy — trace + XLA compile happen
        # on the first call, so THAT is what the build timer must wrap
        with timed(f"sharded program trace+compile+first-run [{agg.name}/{method}]"):
            with telemetry.span(
                "program-build", agg=agg.name, method=method, ndev=ndev, size=size
            ):
                result = fn(arr, codes_dev)
    else:
        telemetry.count("cache.program_hits")
        # the annotation makes the SPMD dispatch visible inside xprof device
        # traces (jax.profiler.TraceAnnotation) as well as in our own trace
        with telemetry.annotated(
            f"flox:mesh-dispatch[{agg.name}/{method}]", ndev=ndev, size=size
        ):
            result = fn(arr, codes_dev)
    if tm_on:
        # observed wall snapshotted BEFORE the card analysis: its
        # lower+compile must not bill as device time (it would read as
        # drift on the first dispatch)
        dispatch_ms = (perf_counter() - t_dispatch0) * 1e3
        prog = f"mesh[{agg.name}/{method}]"
        telemetry.sample_hbm(program=prog)
        # analytical card of the SPMD program (costmodel plane): lowering
        # re-enters the same shard_map closure, so the card reflects the
        # per-device program actually dispatched
        from .. import costmodel

        costmodel.ensure_card(prog, fn, (arr, codes_dev))
        telemetry.observe_cost(
            prog,
            device_ms=dispatch_ms,
            nbytes=int(getattr(arr, "nbytes", 0))
            + int(getattr(codes_dev, "nbytes", 0)),
            compiles=int(telemetry.METRICS.get("jax.compiles") - compiles0),
            compile_ms=telemetry.METRICS.get("jax.compile_ms") - compile_ms0,
        )
    return result


#: compiled shard_map programs, LRU-bounded: get() renews recency, inserts
#: past capacity evict the single least-recently-served program (the old
#: wholesale clear-at-256 dropped every hot program under sustained
#: mixed-key traffic — exactly the serving workload's shape)
_PROGRAM_CACHE: LRUCache = LRUCache(maxsize=256)


def _agg_cache_key(agg: Aggregation):
    """Hashable identity of a resolved Aggregation for the program cache.
    Registry-derived aggs with equal keys trace identical programs."""

    def h(v):
        if isinstance(v, (list, tuple)):
            return tuple(h(x) for x in v)
        if isinstance(v, float) and np.isnan(v):
            return "__nan__"
        if isinstance(v, dict):
            return tuple(sorted((k, h(x)) for k, x in v.items()))
        if callable(v):
            # id() too: distinct lambdas share a "<lambda>" qualname and must
            # not collide in the program cache
            return (getattr(v, "__qualname__", repr(v)), id(v))
        return repr(v) if isinstance(v, np.generic) else v

    from ..aggregations import FusedAggregation

    # a fused plan's per-statistic identity (final fill/dtype/kwargs per
    # slot) lives in its member aggs, not the shared legs — two plans with
    # identical legs but different per-stat fills must not share a program
    fused_extra = ()
    if isinstance(agg, FusedAggregation):
        fused_extra = tuple(
            (a.name, h(a.final_fill_value), str(a.final_dtype), h(a.finalize_kwargs))
            for a in agg.aggs
        )

    return (
        agg.name,
        h(agg.chunk),
        h(agg.combine),
        h(agg.numpy),
        h(agg.fill_value.get("intermediate", ())),
        h(agg.final_fill_value),
        str(agg.final_dtype),
        h(agg.finalize_kwargs),
        agg.min_count,
        agg.reduction_type,
        fused_extra,
    )


def _apply_final_fill(result, counts, agg: Aggregation):
    """Mask groups below the contribution threshold with the final fill.

    Shared by every mesh program (map-reduce/cohorts finalize AND
    blockwise), with the promotion+where core in ONE place —
    ``aggregations._masked_fill``, which the fused multi-statistic
    finalize also uses — so the promotion rules cannot drift apart.
    Counts are (..., size) with the group axis LAST, exactly like the
    trailing dims of the result, so standard right-aligned broadcasting
    (inside ``_masked_fill``) covers both extra leading dims (quantile's
    q) and matching shapes.
    """
    from ..aggregations import _masked_fill

    final_fill = agg.final_fill_value
    if isinstance(final_fill, str):
        raise TypeError("string fill values are not supported on device")
    threshold = max(agg.min_count, 1)
    return _masked_fill(result, counts < threshold, final_fill)


def _build_program(
    agg, *, size, size_pad, method, axis_name, shard_len, nat,
    cohort_perm=None, blocked=False, ndev=1,
):
    import jax
    import jax.numpy as jnp

    if cohort_perm is not None:
        # slot -> group (size_pad; `size` = zero-pad column) and its inverse
        # group -> slot (size) — static constants baked into the program
        perm_c = jnp.asarray(cohort_perm, dtype=jnp.int32)
        inv_np = np.empty(size, dtype=np.int64)
        valid = cohort_perm < size
        inv_np[cohort_perm[valid]] = np.flatnonzero(valid)
        inv_c = jnp.asarray(inv_np, dtype=jnp.int32)

    skipna = agg.name.startswith("nan") or agg.name == "count"
    # min_count thresholds count non-NaN contributions (the reference appends
    # nanlen regardless of skipna, aggregations.py:1005-1014)
    count_skipna = skipna or agg.min_count > 0

    def finalize(combined, counts):
        return _finalize_combined(agg, combined, counts)

    def numpy_kernel(f, codes_sh, arr_sh, **extra):
        """Invoke one whole-reduction (agg.numpy) kernel — the SINGLE place
        the orderstat and blockwise programs assemble finalize_kwargs/nat,
        so the two paths cannot drift."""
        kw = dict(agg.finalize_kwargs)
        if nat:
            kw["nat"] = True
        kw.update(extra)
        if callable(f):
            return f(codes_sh, arr_sh, size=size, fill_value=None, **kw)
        from ..kernels import generic_kernel

        return generic_kernel(f, codes_sh, arr_sh, size=size, fill_value=None, **kw)

    def orderstat_program(arr_sh, codes_sh):
        """Distributed quantile/median: ONE kernel call whose radix-select
        counting passes psum across shards (kernels._quantile_impl
        axis_name=). The selected value is reconstructed bit-by-bit from
        the global counts — never gathered from any single shard — so the
        result is replicated by construction. Capability the reference
        does not have: it forces method='blockwise' for order statistics
        (core.py:685-709)."""
        counts_local = _local_counts(codes_sh, arr_sh, size, count_skipna, nat)
        counts = jax.lax.psum(counts_local, axis_name)
        result = numpy_kernel(agg.numpy[0], codes_sh, arr_sh, axis_name=axis_name)
        return _apply_final_fill(result, counts, agg)

    def mapreduce_program(arr_sh, codes_sh):
        counts_local = _local_counts(codes_sh, arr_sh, size, count_skipna, nat)
        counts = jax.lax.psum(counts_local, axis_name)

        if agg.reduction_type == "argreduce":
            val_f, arg_f = agg.chunk  # e.g. ("max", "argmax")
            from ..kernels import generic_kernel

            kw = {"nat": True} if nat else {}
            val = generic_kernel(
                val_f, codes_sh, arr_sh, size=size,
                fill_value=agg.fill_value["intermediate"][0], **kw,
            )
            local_arg = generic_kernel(arg_f, codes_sh, arr_sh, size=size, fill_value=-1, **kw)
            offset = _flat_axis_index(axis_name).astype(jnp.int64 if utils.x64_enabled() else jnp.int32) * shard_len
            gidx = jnp.where(local_arg >= 0, local_arg + offset, -1)
            inters = [val, gidx]
        elif agg.combine in (("first",), ("last",)):
            offset = _flat_axis_index(axis_name).astype(jnp.int32) * shard_len
            val, pos = _local_firstlast(
                codes_sh, arr_sh, size, skipna=skipna,
                last=agg.combine == ("last",), nat=nat, offset=offset,
            )
            inters = [val, pos]
        else:
            inters = _local_chunk(agg, codes_sh, arr_sh, size, nat)
        combined = _combine_intermediates(agg, inters, axis_name, nat)
        return finalize(combined, counts)

    def blocked_cohorts_program(arr_sh, codes_sh):
        """Huge-label-space variant (VERDICT r3 #6): no dense (..., size)
        buffer ever materializes. A fori_loop walks the ndev owner blocks;
        each iteration chunk-reduces only that block's groups into a
        (..., size/ndev) buffer, psums it (replicated), and the owner
        mask-keeps its slice. Communication totals one psum of (..., size)
        — the same bytes as plain map-reduce — but peak HBM is
        (..., size/ndev) x O(1) buffers. The data makes ndev passes, the
        price of the memory ceiling."""
        me = _flat_axis_index(axis_name)
        b = size_pad // ndev

        def block(d):
            in_block = (codes_sh >= d * b) & (codes_sh < (d + 1) * b)
            bc = jnp.where(in_block, codes_sh - d * b, -1)
            counts = jax.lax.psum(
                _local_counts(bc, arr_sh, b, count_skipna, nat), axis_name
            )
            outs = []
            for inter, op in zip(_local_chunk(agg, bc, arr_sh, b, nat), agg.combine):
                outs.append(
                    _combine_var(inter, axis_name)
                    if op == "var"
                    else _combine_simple(op, inter, axis_name, nat=nat and not skipna)
                )
            return counts, outs

        c0, o0 = block(0)
        keep0 = me == 0
        carry0 = jax.tree.map(lambda x: jnp.where(keep0, x, jnp.zeros_like(x)), (c0, o0))

        def body(d, carry):
            c, o = block(d)
            keep = me == d
            return jax.tree.map(lambda new, acc: jnp.where(keep, new, acc), (c, o), carry)

        counts_own, owned = jax.lax.fori_loop(1, ndev, body, carry0)
        result_own = finalize(owned, counts_own)
        full = jax.lax.all_gather(
            jnp.moveaxis(result_own, -1, 0), axis_name, tiled=True
        )
        return _crop(jnp.moveaxis(full, 0, -1), size)

    def cohorts_program(arr_sh, codes_sh):
        # psum_scatter needs every intermediate to be additive; route others
        # through map-reduce (matching how the reference falls back to
        # map-reduce when cohort detection finds nothing to exploit)
        if not _is_additive(agg):
            return mapreduce_program(arr_sh, codes_sh)
        if blocked:
            return blocked_cohorts_program(arr_sh, codes_sh)

        from ..kernels import generic_kernel

        def pad_groups(x):
            if size_pad == size:
                return x
            widths = [(0, 0)] * (x.ndim - 1) + [(0, size_pad - size)]
            return jnp.pad(x, widths)

        def to_slots(x):
            """Pad the group axis and place groups in their ownership slots
            (identity layout when no cohort alignment was found)."""
            x = pad_groups(x)
            if cohort_perm is not None:
                x = jnp.take(x, perm_c, axis=-1)
            return x

        def from_slots(full):
            """Gathered slot layout -> original group order, cropped."""
            if cohort_perm is not None:
                return jnp.take(full, inv_c, axis=-1)
            return _crop(full, size)

        counts_local = to_slots(_local_counts(codes_sh, arr_sh, size, count_skipna, nat))
        counts_own = jax.lax.psum_scatter(
            jnp.moveaxis(counts_local, -1, 0), axis_name, scatter_dimension=0, tiled=True
        )
        counts_own = jnp.moveaxis(counts_own, 0, -1)

        inters = _local_chunk(agg, codes_sh, arr_sh, size, nat)
        owned = []
        for inter, op in zip(inters, agg.combine):
            if op == "var":
                # scatter each leaf; the Chan adjustment needs the scattered
                # totals, so do it leaf-wise after scattering sums
                m2, total, nn = inter.arrays
                mu_d = total / jnp.where(nn > 0, nn, 1)
                big_t = _pscatter(to_slots(total), axis_name)
                big_n = _pscatter(to_slots(nn), axis_name)
                # mu over owned slice must be compared against each shard's
                # mu_d — requires the adjustment before scattering:
                # psum_scatter(m2 + n*(mu_d - mu)^2) with mu broadcast back.
                mu = big_t / jnp.where(big_n > 0, big_n, 1)
                mu_full = _unscatter_broadcast(mu, axis_name)
                adj = nn * (mu_d - from_slots(mu_full)) ** 2
                big_m2 = _pscatter(to_slots(m2 + adj), axis_name)
                owned.append(MultiArray((big_m2, big_t, big_n)))
            else:
                owned.append(_pscatter(to_slots(inter), axis_name))

        result_own = finalize(owned, counts_own)
        # replicate: gather the owned slices back into the full group axis
        full = jax.lax.all_gather(jnp.moveaxis(result_own, -1, 0), axis_name, tiled=True)
        return from_slots(jnp.moveaxis(full, 0, -1))

    def blockwise_program(arr_sh, codes_sh):
        counts_local = _local_counts(codes_sh, arr_sh, size, count_skipna, nat)
        locals_ = [numpy_kernel(f, codes_sh, arr_sh) for f in agg.numpy]
        if agg.reduction_type == "argreduce" and len(locals_) > 1:
            result_local = locals_[1]
        elif agg.finalize is not None and len(agg.numpy) > 1:
            # multi-stage custom Aggregation (see core._reduce_blockwise)
            result_local = agg.finalize(*locals_, **agg.finalize_kwargs)
        else:
            result_local = locals_[0]
        if agg.reduction_type == "argreduce":
            offset = _flat_axis_index(axis_name).astype(jnp.int32) * shard_len
            result_local = jnp.where(result_local >= 0, result_local + offset, -1)
        # owner = the shard that saw this group's elements (precondition:
        # exactly one, after reshard_for_blockwise)
        counts_all = jax.lax.all_gather(counts_local, axis_name)  # (ndev, ..., size)
        res_all = jax.lax.all_gather(result_local, axis_name)  # (ndev, *new, ..., size)
        owner = jnp.argmax(counts_all > 0, axis=0)  # (..., size)
        extra = res_all.ndim - 1 - owner.ndim  # new dims (e.g. quantile's q)
        pick = jnp.broadcast_to(
            owner.reshape((1,) * extra + owner.shape), res_all.shape[1:]
        )
        result = jnp.take_along_axis(res_all, pick[None], axis=0)[0]
        counts = jax.lax.psum(counts_local, axis_name)
        return _apply_final_fill(result, counts, agg)

    if method == "map-reduce":
        return orderstat_program if agg.blockwise_only else mapreduce_program
    if method == "cohorts":
        return cohorts_program
    if method == "blockwise":
        return blockwise_program
    raise ValueError(f"Unknown method {method!r}")


def _pscatter(x, axis_name):
    """psum_scatter over the trailing (group) axis; returns the owned slice."""
    import jax
    import jax.numpy as jnp

    moved = jnp.moveaxis(x, -1, 0)
    out = jax.lax.psum_scatter(moved, axis_name, scatter_dimension=0, tiled=True)
    return jnp.moveaxis(out, 0, -1)


def _unscatter_broadcast(x_own, axis_name):
    """all_gather an owned slice back to the full (padded) group axis."""
    import jax
    import jax.numpy as jnp

    moved = jnp.moveaxis(x_own, -1, 0)
    full = jax.lax.all_gather(moved, axis_name, tiled=True)
    return jnp.moveaxis(full, 0, -1)


def _crop(x, size):
    return x[..., :size]
