"""Distributed execution over a TPU mesh (L5).

This package is the TPU-native replacement for the reference's chunked-array
backends (/root/reference/flox/dask.py, cubed.py, dask_array_ops.py): instead
of building lazy task graphs whose combine is concatenate-then-reduce, the
whole map-reduce is ONE jitted SPMD program — ``shard_map`` over a
``jax.sharding.Mesh``, with XLA collectives as the combine:

=====================  ==========================================
reference (dask)        flox_tpu (mesh)
=====================  ==========================================
blockwise chunk_reduce  shard-local chunk_reduce inside shard_map
``_simple_combine``     ``lax.psum`` / ``pmax`` / ``pmin``
``_grouped_combine``    all_gather + static fold (small tails)
cohorts graph surgery   ``lax.psum_scatter`` group ownership
Blelloch scan binop     per-shard carries exchanged via all_gather
=====================  ==========================================

Dense, shape-static intermediates over ``expected_groups`` (the reference's
``reindex=True``) are load-bearing here: they are what make every shard's
contribution identical in shape, which is exactly what collectives need.
"""

from .mesh import axis_size, make_mesh, shard_map
from .mapreduce import sharded_groupby_reduce
from .scan import sharded_groupby_scan

__all__ = [
    "axis_size",
    "make_mesh",
    "shard_map",
    "sharded_groupby_reduce",
    "sharded_groupby_scan",
]
