"""Sharded grouped scans: the mesh Blelloch scan (L5).

Parity target: the reference's dask scan pipeline (dask.py:576-663) —
``cumreduction(method="blelloch")`` with ``chunk_scan`` / ``grouped_reduce``
/ ``scan_binary_op`` (scan.py:318-352, aggregations.py:792-846).

Mesh realization, one jitted SPMD program:

1. each shard runs the segmented within-shard scan (the same
   ``associative_scan`` kernel as the eager path);
2. each shard computes its per-group block summary (sum of the block for
   cumsum; last valid value for ffill) — the Blelloch "preop";
3. carries are exchanged with ONE ``all_gather`` (ndev × size values) and
   each shard folds its exclusive prefix — the cross-shard "binop". For
   cumsum that fold is a select-then-sum over the gathered (ndev, size)
   block summaries; for ffill it picks the nearest preceding shard with a
   valid value;
4. the carry is gathered back per element through the group codes.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .. import utils
from ..aggregations import Scan
from .mapreduce import _cached_mesh_default, _flat_axis_index, _norm_axes, _pad_to
from .mesh import shard_map

_SCAN_CACHE: dict = {}


def sharded_groupby_scan(
    array: Any,
    codes: Any,
    scan: Scan,
    *,
    size: int,
    mesh: Any = None,
    axis_name: str | tuple[str, ...] = "data",
    dtype: Any = None,
    method: str = "blelloch",
    nat: bool = False,
) -> Any:
    """Sharded grouped scan over the trailing axis. Returns same shape as
    ``array`` (padded positions stripped).

    ``method="blockwise"`` skips the carry exchange entirely — valid only
    when every group is shard-local (validated host-side; the analogue of
    the reference's blockwise scan after rechunk_for_blockwise,
    scan.py:48-78 + dask.py:624-651).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        mesh = _cached_mesh_default()
    axes = _norm_axes(axis_name, mesh)
    ndev = int(np.prod([mesh.shape[a] for a in axes]))

    if method == "blockwise":
        _validate_shard_local(np.asarray(codes).reshape(-1), ndev)

    arr = utils.asarray_device(array)
    if dtype is not None:
        arr = arr.astype(dtype)
    codes_dev = jnp.asarray(np.asarray(codes), dtype=jnp.int32)
    n = codes_dev.shape[0]
    pad = _pad_to(n, ndev)
    if pad:
        codes_dev = jnp.concatenate([codes_dev, jnp.full((pad,), -1, dtype=jnp.int32)])
        widths = [(0, 0)] * (arr.ndim - 1) + [(0, pad)]
        arr = jnp.pad(arr, widths)

    spec_entry = axes if len(axes) > 1 else axes[0]
    in_specs = (P(*([None] * (arr.ndim - 1) + [spec_entry])), P(spec_entry))
    out_specs = P(*([None] * (arr.ndim - 1) + [spec_entry]))

    from ..options import trace_fingerprint

    cache_key = (scan.name, size, axes, mesh, arr.ndim, str(arr.dtype), method, nat, trace_fingerprint())
    from .. import telemetry

    fn = _SCAN_CACHE.get(cache_key)
    if fn is None:
        telemetry.count("cache.scan_misses")
        if method == "blockwise":
            program = _build_blockwise_scan_program(scan, size=size, nat=nat)
        else:
            program = _build_scan_program(scan, size=size, axis_name=axes, nat=nat)
        fn = jax.jit(
            shard_map(program, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
        )
        if len(_SCAN_CACHE) > 256:
            _SCAN_CACHE.clear()
        _SCAN_CACHE[cache_key] = fn
    else:
        telemetry.count("cache.scan_hits")
    with telemetry.annotated(
        f"flox:mesh-scan[{scan.name}/{method}]", ndev=ndev, size=size
    ):
        out = fn(arr, codes_dev)
    if pad:
        out = out[..., :n]
    return out


def build_stream_scan_step(scan: Scan, *, size: int, mesh, axis_name="data",
                           nat: bool = False, lead_ndim: int = 0):
    """One jitted shard_map step for the streaming × mesh scan composition:
    ``(slab_sharded, codes_sharded, carry_a, carry_b) ->
    (out_sharded, new_carry_a, new_carry_b)`` — the within-slab distributed
    Blelloch (identical to the in-memory program) plus the cross-slab
    carry fold. Carry state: (per-group sums, had-NaT int8) for
    cumsum-mode; (per-group edge value, has int8) for ffill/bfill."""
    from jax.sharding import PartitionSpec as P

    from ..pipeline import maybe_donate

    axes = _norm_axes(axis_name, mesh)
    program = _build_scan_program(
        scan, size=size, axis_name=axes, nat=nat, stream_carry=True
    )
    spec_entry = axes if len(axes) > 1 else axes[0]
    arr_spec = P(*([None] * lead_ndim + [spec_entry]))

    # the cross-slab carry pair is donated: updated in place across slabs
    return maybe_donate(
        shard_map(
            program, mesh=mesh,
            in_specs=(arr_spec, P(spec_entry), P(), P()),
            out_specs=(arr_spec, P(), P()),
            check_vma=False,
        ),
        donate_argnums=(2, 3),
    )


def _validate_shard_local(codes: np.ndarray, ndev: int) -> None:
    """Blockwise precondition: every group's positions within one shard."""
    n = codes.shape[0]
    shard_len = -(-n // ndev) if n else 1
    valid = codes >= 0
    if not valid.any():
        return
    shard_of = np.arange(n) // shard_len
    order = np.argsort(codes[valid], kind="stable")
    grp = codes[valid][order]
    shd = shard_of[valid][order]
    boundaries = np.flatnonzero(np.diff(grp)) + 1
    firsts = np.r_[0, boundaries]
    lasts = np.r_[boundaries, grp.size] - 1
    bad = np.flatnonzero(shd[firsts] != shd[lasts])
    if bad.size:
        i = firsts[bad[0]]
        raise ValueError(
            f"method='blockwise' needs every group on one shard, but group "
            f"{int(grp[i])} spans shards {int(shd[i])}..{int(shd[lasts[bad[0]]])}; "
            "reshard first (rechunk.reshard_for_blockwise) or use "
            "method='blelloch'."
        )


def _build_blockwise_scan_program(scan: Scan, *, size, nat=False):
    """Shard-local groups: the within-shard segmented scan IS the answer —
    zero collectives (parity: the reference's blockwise scan, dask.py:624-651)."""
    from ..kernels import generic_kernel

    def program(arr_sh, codes_sh):
        return generic_kernel(scan.scan, codes_sh, arr_sh, size=size, nat=nat)

    return program


def _build_scan_program(scan: Scan, *, size, axis_name, nat=False, stream_carry=False):
    """``stream_carry=True`` builds the STREAMING variant: the program takes
    a replicated cross-slab carry state and returns ``(out, new_state)`` —
    the same within-slab Blelloch plus the slab-boundary fold, so
    out-of-core scans distribute over the mesh with the identical carry
    semantics (streaming.streaming_groupby_scan mesh path)."""
    import jax
    import jax.numpy as jnp

    from ..kernels import generic_kernel

    def program(arr_sh, codes_sh, *carry_state):
        # 1. within-shard segmented scan
        local = generic_kernel(scan.scan, codes_sh, arr_sh, size=size, nat=nat)

        if scan.mode == "apply_binary_op":
            if nat:
                # int64-viewed datetimes: NaT is a sentinel, not an IEEE
                # value, so — unlike float NaN, which rides the carry sum
                # arithmetically — the block summaries need an explicit
                # had-NaT channel (parity: the reference's scan binop
                # handles datetime uniformly, aggregations.py:792-846).
                # Block sums are NaT-as-zero; the non-skipna poison is
                # re-applied from the channel after the fold.
                from ..kernels import _NAT_INT

                is_nat = arr_sh == jnp.asarray(_NAT_INT, arr_sh.dtype)
                summed = jnp.where(is_nat, jnp.zeros((), arr_sh.dtype), arr_sh)
            else:
                summed = arr_sh
            # 2. block summary: per-group sum of this shard
            block = generic_kernel(
                scan.reduction, codes_sh, summed, size=size, fill_value=0
            )
            block = block.astype(local.dtype)
            # 3. exclusive prefix across shards: gather (ndev, ..., size) and
            # fold devices strictly before mine. A select-then-sum, not a
            # masked multiply: NaN blocks (cumsum propagation) would poison
            # every carry through NaN * 0. The had-NaT channel (non-skipna
            # datetime poisoning) rides the SAME gather as an extra leading
            # slot — the carry exchange stays ONE collective.
            poison_channel = nat and scan.scan == "cumsum"
            if poison_channel:
                had = generic_kernel(
                    "sum", codes_sh, is_nat.astype(jnp.int32), size=size,
                    fill_value=0,
                ).astype(block.dtype)
                payload = jnp.stack([block, had])  # (2, ..., size)
                g = jax.lax.all_gather(payload, axis_name)  # (ndev, 2, ..., size)
                gathered = g[:, 0]
                g_had = g[:, 1] > 0
            else:
                gathered = jax.lax.all_gather(block, axis_name)  # (ndev, ..., size)
            ndev = gathered.shape[0]
            me = _flat_axis_index(axis_name)
            mask = (jnp.arange(ndev) < me).reshape((ndev,) + (1,) * (gathered.ndim - 1))
            carry = jnp.sum(
                jnp.where(mask, gathered, jnp.zeros((), gathered.dtype)), axis=0
            )  # (..., size)
            # 4. add the carry through the codes
            safe = jnp.where(codes_sh < 0, size, codes_sh)
            carry_pad = jnp.concatenate(
                [carry, jnp.zeros(carry.shape[:-1] + (1,), carry.dtype)], axis=-1
            )
            per_elem = jnp.take(carry_pad, safe, axis=-1)
            out = local + per_elem
            if stream_carry:
                # cross-slab carry: previous slabs' per-group totals add to
                # every element; the new state folds THIS slab's global
                # block totals in (psum = all shards of the slab)
                prev_sums = carry_state[0]
                prev_pad = jnp.concatenate(
                    [prev_sums, jnp.zeros(prev_sums.shape[:-1] + (1,), prev_sums.dtype)],
                    axis=-1,
                )
                out = out + jnp.take(prev_pad, safe, axis=-1).astype(out.dtype)
                new_sums = prev_sums + jax.lax.psum(block, axis_name).astype(prev_sums.dtype)
            if poison_channel:
                # non-skipna: a NaT anywhere earlier in the group poisons
                # every later element. In-shard poisoning is already in
                # ``local`` (== NaT sentinel); cross-shard comes from the
                # had-NaT channel folded over shards strictly before me.
                poison = jnp.any(mask & g_had, axis=0)  # (..., size)
                if stream_carry:
                    poison = poison | (carry_state[1] > 0)  # earlier slabs
                poison_pad = jnp.concatenate(
                    [poison, jnp.zeros(poison.shape[:-1] + (1,), bool)], axis=-1
                )
                poison_e = jnp.take(poison_pad, safe, axis=-1)
                nat_val = jnp.asarray(_NAT_INT, out.dtype)
                out = jnp.where(poison_e | (local == nat_val), nat_val, out)
            # skipna (nancumsum): NaT counts as zero on the eager path, so
            # the plain carry add is already exact — no sentinel survives
            # the within-shard scan
            if stream_carry:
                slab_had = (
                    (jnp.any(g_had, axis=0).astype(jnp.int8) | carry_state[1])
                    if poison_channel
                    else carry_state[1]
                )
                return out, new_sums, slab_had
            return out

        # ffill/bfill: carry = last (first) valid value per group in shards
        # strictly before (after) me
        reverse = scan.name == "bfill"
        is_float = jnp.issubdtype(arr_sh.dtype, jnp.floating)
        valid_f = generic_kernel(
            "nanlen", codes_sh, arr_sh, size=size, nat=nat
        )  # per-group valid counts this shard
        last_val = generic_kernel(
            "nanlast" if not reverse else "nanfirst",
            codes_sh,
            arr_sh,
            size=size,
            fill_value=jnp.nan if is_float else 0,
            nat=nat,
        )
        g_vals = jax.lax.all_gather(last_val, axis_name)  # (ndev, ..., size)
        g_valid = jax.lax.all_gather(valid_f > 0, axis_name)
        ndev = g_vals.shape[0]
        me = _flat_axis_index(axis_name)
        before = (jnp.arange(ndev) < me) if not reverse else (jnp.arange(ndev) > me)
        before = before.reshape((ndev,) + (1,) * (g_vals.ndim - 1))
        eligible = g_valid & before
        # index of the closest eligible shard (max index for ffill, min for bfill)
        dev_idx = jnp.arange(ndev).reshape((ndev,) + (1,) * (g_vals.ndim - 1))
        if not reverse:
            pick = jnp.max(jnp.where(eligible, dev_idx, -1), axis=0)
        else:
            pick = jnp.min(jnp.where(eligible, dev_idx, ndev), axis=0)
        has_carry = (pick >= 0) & (pick < ndev)
        pick_c = jnp.clip(pick, 0, ndev - 1)
        carry = jnp.take_along_axis(g_vals, pick_c[None], axis=0)[0]
        # apply: positions still missing after the local fill take the carry
        safe = jnp.where(codes_sh < 0, size, codes_sh)

        def gather_groups(x):
            pad = jnp.zeros(x.shape[:-1] + (1,), x.dtype)
            return jnp.take(jnp.concatenate([x, pad], axis=-1), safe, axis=-1)

        carry_e = gather_groups(carry)
        has_e = gather_groups(has_carry.astype(jnp.int8)) > 0
        from ..kernels import _nan_mask

        mask = _nan_mask(local, nat)  # None when nothing can be missing
        still = ~mask if mask is not None else jnp.zeros(local.shape, bool)
        out = jnp.where(still & has_e & (codes_sh >= 0), carry_e, local)
        if not stream_carry:
            return out
        # cross-slab: positions STILL missing after the within-slab fill
        # take the previous slabs' carry; the new state picks this slab's
        # edge value (last valid shard for ffill, first for bfill) over
        # ALL shards, keeping the old value for groups absent here
        prev_val, prev_has = carry_state
        mask2 = _nan_mask(out, nat)
        still2 = ~mask2 if mask2 is not None else jnp.zeros(out.shape, bool)
        out = jnp.where(
            still2 & (gather_groups(prev_has) > 0) & (codes_sh >= 0),
            gather_groups(prev_val).astype(out.dtype),
            out,
        )
        any_valid = jnp.any(g_valid, axis=0)  # (..., size), over ALL shards
        if not reverse:
            pick_all = jnp.max(jnp.where(g_valid, dev_idx, -1), axis=0)
        else:
            pick_all = jnp.min(jnp.where(g_valid, dev_idx, ndev), axis=0)
        pick_all_c = jnp.clip(pick_all, 0, ndev - 1)
        slab_edge = jnp.take_along_axis(g_vals, pick_all_c[None], axis=0)[0]
        new_val = jnp.where(any_valid, slab_edge.astype(prev_val.dtype), prev_val)
        new_has = prev_has | any_valid.astype(prev_has.dtype)
        return out, new_val, new_has

    return program
