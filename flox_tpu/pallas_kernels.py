"""Pallas TPU kernels for the hot segment reductions (L1, below kernels.py).

Why a custom kernel: XLA lowers ``segment_sum`` to scatter-add, which
serializes on the VPU; the one-hot GEMM path (kernels._seg_matmul_sum) rides
the MXU but pays extra HBM traffic for its exactness marker columns. This
kernel gets both: the data streams HBM→VMEM exactly once, and each tile's
contribution is an **in-VMEM** one-hot matmul on the MXU — the one-hot and
the marker masks never touch HBM.

Layout: the kernel reads ``data`` in its natural trailing-reduce layout
(K, N) — i.e. the transpose of the (N, K) logical view ``_seg`` passes in.
Because every caller reaches ``_seg`` through ``_to_leading`` (a lazy
``moveaxis(-1, 0)``), the two transposes cancel under XLA and the HBM
buffer is consumed **in place**: no transposed copy, which at benchmark
scale (~7 GB) is the difference between running and OOM. The data is NOT
padded either — TPU Pallas supports non-divisible block shapes (edge-block
out-of-bounds reads are undefined), and undefined values are harmless here:
out-of-range N columns carry the sentinel code (all-zero one-hot row, so
they contract to exactly 0.0 against every group) and out-of-range K rows
are sliced off the output. Only ``codes`` (tiny) is padded, with the
sentinel.

Grid = (k_tiles, n_tiles) with the output block revisited across the n axis
(sequential TPU grid → accumulate with an init at n==0, the standard
reduction pattern). Non-finite values are zero-filled in VMEM and NaN/±inf
markers accumulate in three extra outputs so IEEE propagation is re-applied
exactly.

Reference analogue: the numpy_groupies bincount kernels this replaces
(aggregate_npg.py:7-126) — but tiled for the memory hierarchy the guide
describes (pallas_guide.md: HBM→VMEM→MXU).
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = [
    "segment_sum_pallas",
    "segment_sum_raw_pallas",
    "segment_minmax_pallas",
    "segment_multistat_pallas",
    "pallas_available",
]


def pallas_available() -> bool:
    try:
        import jax.experimental.pallas  # noqa: F401

        return True
    except ImportError:  # pragma: no cover
        return False


def _two_sum(a, b):
    """Error-free transformation (Knuth): s + err == a + b exactly, with s
    the rounded f32 sum. Branch-free, 6 VPU flops; relies on XLA not
    reassociating floating-point (it does not, absent fast-math)."""
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def _kernel(
    codes_ref, data_ref, out_ref, nan_ref, pos_ref, neg_ref, comp_ref=None,
    *, size_p, n_tile, accum,
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(1)  # position along the reduced (N) axis

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)
        nan_ref[:] = jnp.zeros_like(nan_ref)
        pos_ref[:] = jnp.zeros_like(pos_ref)
        neg_ref[:] = jnp.zeros_like(neg_ref)
        if accum != "plain":
            comp_ref[:] = jnp.zeros_like(comp_ref)

    codes = codes_ref[0, :]  # (n_tile,)
    data = data_ref[:]  # (k_tile, n_tile)
    onehot = (
        codes[:, None] == jax.lax.broadcasted_iota(jnp.int32, (n_tile, size_p), 1)
    ).astype(data.dtype)  # (n_tile, size_p) — lives only in VMEM

    isnan = jnp.isnan(data)
    ispos = jnp.isposinf(data)
    isneg = jnp.isneginf(data)
    nonfinite = isnan | ispos | isneg
    zeroed = jnp.where(nonfinite, jnp.zeros((), data.dtype), data)

    def contract(tile, precision):
        # (n_tile, size_p)ᵀ-contract-(k_tile, n_tile) -> (size_p, k_tile).
        # Edge-block garbage in `tile` multiplies a zero one-hot row (its
        # column carries the sentinel code), contributing exactly 0.0.
        return jax.lax.dot_general(
            onehot,
            tile,
            dimension_numbers=(((0,), (1,)), ((), ())),
            preferred_element_type=out_ref.dtype,
            precision=precision,
        )

    _accum_update(out_ref, comp_ref, onehot, zeroed, contract, accum)

    # Marker contracts are the MXU-bound tail: at HIGHEST each costs as much
    # as the sums pass (f32 = multi-pass bf16 on the MXU) and they triple the
    # kernel's FLOPs. Two savings: (1) 0/1 masks are exact in bf16 and the
    # MXU accumulates into f32 natively, so DEFAULT precision (single pass)
    # loses nothing; (2) all-finite tiles — the overwhelmingly common case —
    # skip the contracts entirely on a data-dependent scalar branch.
    @pl.when(jnp.any(nonfinite))
    def _markers():
        import jax as _jax

        d = _jax.lax.Precision.DEFAULT
        nan_ref[:] += contract(isnan.astype(data.dtype), d)
        pos_ref[:] += contract(ispos.astype(data.dtype), d)
        neg_ref[:] += contract(isneg.astype(data.dtype), d)


def _accum_update(out_ref, comp_ref, onehot, zeroed, contract, accum):
    """Cross-tile accumulation of one tile's contraction into the running
    (out_ref, comp_ref) state, under the selected discipline — shared by
    the dense megakernel grid and the radix-binning blocked grid."""
    import jax
    import jax.numpy as jnp

    if accum == "kahan":
        # Kahan summation across the sequential n-grid: recovers most of the
        # bits a plain f32 running sum loses over many tiles — the accuracy
        # story on TPUs, where float64 hardware does not exist (the eager
        # CPU path gets true f64 via jax_enable_x64 instead).
        y = contract(zeroed, jax.lax.Precision.HIGHEST) - comp_ref[:]
        t = out_ref[:] + y
        comp_ref[:] = (t - out_ref[:]) - y
        out_ref[:] = t
    elif accum == "dd":
        # Double-double: the running sum is an unevaluated (hi, lo) f32
        # pair (out_ref, comp_ref), ~49 effective mantissa bits. Two error
        # sources are attacked separately:
        #  * intra-tile — each value is Dekker-split into a 12-bit-mantissa
        #    high part and an exact low remainder; the one-hot products are
        #    exact (x·1), so each contraction accumulates far fewer
        #    significant bits per addend and the two partial sums together
        #    carry (nearly) the full per-tile sum;
        #  * cross-tile — the partial sums merge into the (hi, lo) carry
        #    through error-free two_sum transforms, never dropping a
        #    rounding remainder on the floor.
        acc = out_ref.dtype
        z = zeroed.astype(acc)
        c = z * jnp.asarray(4097.0, acc)  # 2**12 + 1: split 24 -> 12 + 12
        z_hi = c - (c - z)
        z_lo = z - z_hi
        # the split constant overflows for |x| > f32max/4097 ≈ 8.3e34; such
        # values keep their low bits in the high part (intra-tile rounding
        # at that magnitude is the documented reordered-summation boundary)
        huge = jnp.abs(z) > jnp.asarray(8e34, acc)
        z_hi = jnp.where(huge, z, z_hi)
        z_lo = jnp.where(huge, jnp.zeros((), acc), z_lo)
        onehot_a = onehot.astype(acc)

        def contract_a(tile):
            return jax.lax.dot_general(
                onehot_a, tile,
                dimension_numbers=(((0,), (1,)), ((), ())),
                preferred_element_type=acc,
                precision=jax.lax.Precision.HIGHEST,
            )

        s, e1 = _two_sum(contract_a(z_hi), contract_a(z_lo))
        hi, e2 = _two_sum(out_ref[:], s)
        lo = comp_ref[:] + (e1 + e2)
        # renormalize so hi is the best single-f32 representation; Knuth
        # two_sum, not Fast2Sum — after catastrophic cross-tile
        # cancellation |lo| can exceed |hi| and Fast2Sum would drop the
        # carry's low-order bits exactly where they matter most
        hi2, lo2 = _two_sum(hi, lo)
        out_ref[:] = hi2
        comp_ref[:] = lo2
    else:
        out_ref[:] += contract(zeroed, jax.lax.Precision.HIGHEST)


@functools.lru_cache(maxsize=128)
def _build(
    k_pad: int, n_pad: int, size_p: int, dtype_str: str, acc_str: str, n_tile: int,
    k_tile: int, interpret: bool, accum: str,
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    kern = functools.partial(_kernel, size_p=size_p, n_tile=n_tile, accum=accum)
    k_tiles = k_pad // k_tile
    grid = (k_tiles, n_pad // n_tile)
    # Accumulator blocks are ``acc_str`` (f32 for bf16 data): the data tile
    # streams HBM→VMEM at its narrow width and the MXU contracts bf16×bf16
    # into f32 natively — a bf16 running sum would saturate at 256.
    acc = jnp.dtype(acc_str)
    # the Kahan compensation / double-double lo term rides as a 5th output
    # block (revisited per k-tile like the sums); pallas scratch does not
    # persist across the k grid axis, an output block does. Plain builds
    # skip it entirely.
    n_out = 4 if accum == "plain" else 5
    # outputs are padded to the block grid (they are tiny — size_p rows);
    # the data input is not (see module docstring).
    out_shape = [jax.ShapeDtypeStruct((size_p, k_pad), acc)] * n_out

    fn = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n_tile), lambda i, j: (0, j)),  # codes
            pl.BlockSpec((k_tile, n_tile), lambda i, j: (i, j)),  # data (K, N)
        ],
        out_specs=[pl.BlockSpec((size_p, k_tile), lambda i, j: (0, i))] * n_out,
        out_shape=out_shape,
        interpret=interpret,
    )
    return jax.jit(fn)


def _tiles(n: int, k: int, size: int):
    """Shared tiling: lane-axis tiles are multiples of 128 (n for the data
    blocks, k for the output blocks), sublane rows multiples of 8."""
    n_tile = 512 if n >= 512 else max(128, -(-n // 128) * 128)
    k_tile = 512 if k >= 512 else max(128, -(-k // 128) * 128)
    n_pad = -(-n // n_tile) * n_tile
    k_pad = -(-k // k_tile) * k_tile
    size_p = max(8, ((size + 7) // 8) * 8)
    return n_tile, k_tile, n_pad, k_pad, size_p


def _minmax_identity(op: str, dtype):
    from .kernels import minmax_identity  # single source of truth

    return minmax_identity(op, dtype)


def _minmax_kernel(codes_ref, data_ref, out_ref, *, size, size_p, op):
    """Per-tile grouped min/max on the VPU: one select + lane-reduce per
    group (MXU cannot do the (max, ·) tropical semiring). VPU work scales
    with ``size``, which is why the policy gates on a group-count cap —
    below it the kernel stays HBM-bound where scatter serializes."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    ident = jnp.asarray(_minmax_identity(op, out_ref.dtype), out_ref.dtype)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.full_like(out_ref, ident)

    codes = codes_ref[0, :]  # (n_tile,)
    data = data_ref[:]  # (k_tile, n_tile)
    combine = jnp.maximum if op == "max" else jnp.minimum
    reduce_ = jnp.max if op == "max" else jnp.min

    rows = []
    for g in range(size):  # static unroll (size is gated small)
        # edge-block garbage lanes carry the sentinel code -> identity
        masked = jnp.where((codes == g)[None, :], data, ident)
        rows.append(reduce_(masked, axis=1))  # (k_tile,)
    tile_red = jnp.stack(rows)  # (size, k_tile)
    if size_p > size:
        tile_red = jnp.concatenate(
            [tile_red, jnp.full((size_p - size, data.shape[0]), ident, out_ref.dtype)]
        )
    out_ref[:] = combine(out_ref[:], tile_red)


@functools.lru_cache(maxsize=128)
def _build_minmax(
    k_pad: int, n_pad: int, size: int, size_p: int, dtype_str: str, n_tile: int,
    k_tile: int, interpret: bool, op: str,
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    kern = functools.partial(_minmax_kernel, size=size, size_p=size_p, op=op)
    fn = pl.pallas_call(
        kern,
        grid=(k_pad // k_tile, n_pad // n_tile),
        in_specs=[
            pl.BlockSpec((1, n_tile), lambda i, j: (0, j)),  # codes
            pl.BlockSpec((k_tile, n_tile), lambda i, j: (i, j)),  # data (K, N)
        ],
        out_specs=pl.BlockSpec((size_p, k_tile), lambda i, j: (0, i)),
        out_shape=jax.ShapeDtypeStruct((size_p, k_pad), jnp.dtype(dtype_str)),
        interpret=interpret,
    )
    return jax.jit(fn)


def segment_minmax_pallas(data, codes, size: int, op: str, *, interpret: bool = False):
    """Segment-min/max ``data`` (N, K...) by ``codes`` (N,) -> (size, K...).

    Missing labels drop out; empty groups return the op's identity (the
    caller's ``_fill_empty`` handles presentation, exactly as for scatter).
    Callers pre-map NaN/NaT to absorbing elements (kernels._make_minmax), so
    no NaN ever reaches this kernel. Same in-place (K, N) consumption as
    ``segment_sum_pallas``.
    """
    import jax.numpy as jnp

    data = jnp.asarray(data)
    orig_shape = data.shape
    n = data.shape[0]
    flat = data.reshape(n, -1)
    k = flat.shape[1]
    flat_t = flat.T  # (K, N) — cancels the caller's moveaxis; no copy

    n_tile, k_tile, n_pad, k_pad, size_p = _tiles(n, k, size)

    codes = jnp.asarray(codes).astype(jnp.int32).reshape(-1)
    codes = jnp.where((codes < 0) | (codes >= size), size_p, codes)
    codes_p = jnp.pad(codes, (0, n_pad - n), constant_values=size_p).reshape(1, n_pad)

    fn = _build_minmax(
        k_pad, n_pad, size, size_p, str(flat.dtype), n_tile, k_tile, interpret, op
    )
    out = fn(codes_p, flat_t)
    return out[:size, :k].reshape((size,) + orig_shape[1:])


def _minmax_accumulate(codes_ref, data_ref, out_ref, *, size, size_p, op):
    """The min/max accumulation of the multi-statistic megakernel: the
    ``_minmax_kernel`` select-reduce, but over RAW data (the megakernel
    stages each tile once for every statistic), so NaN lanes are parked at
    the op's identity here — the skipna semantics; the propagating
    variants re-inject NaN outside from the kernel's NaN marker counts."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    ident = jnp.asarray(_minmax_identity(op, out_ref.dtype), out_ref.dtype)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.full_like(out_ref, ident)

    codes = codes_ref[0, :]  # (n_tile,)
    data = data_ref[:]  # (k_tile, n_tile)
    data = jnp.where(jnp.isnan(data), ident, data)
    combine = jnp.maximum if op == "max" else jnp.minimum
    reduce_ = jnp.max if op == "max" else jnp.min

    rows = []
    for g in range(size):  # static unroll (size is gated small)
        # edge-block garbage lanes carry the sentinel code -> identity
        masked = jnp.where((codes == g)[None, :], data, ident)
        rows.append(reduce_(masked, axis=1))  # (k_tile,)
    tile_red = jnp.stack(rows)  # (size, k_tile)
    if size_p > size:
        tile_red = jnp.concatenate(
            [tile_red, jnp.full((size_p - size, data.shape[0]), ident, out_ref.dtype)]
        )
    out_ref[:] = combine(out_ref[:], tile_red)


def _multistat_kernel(
    codes_ref, data_ref, out_ref, nan_ref, pos_ref, neg_ref, min_ref, max_ref,
    comp_ref=None, *, size, size_p, n_tile, accum,
):
    """The fused multi-statistic megakernel: ONE HBM→VMEM pass per tile
    feeds (a) the compensated one-hot sum contraction with its NaN/±inf
    marker outputs (:func:`_kernel`, verbatim — the sums are bit-identical
    to ``segment_sum_pallas`` at the same tiling) and (b) the VPU
    select-reduce grouped min AND max. Every accumulator — sums,
    compensation, markers, min, max — is an output block revisited across
    the sequential n grid, i.e. resident in VMEM for the whole pass; the
    data is read from HBM exactly once for the entire statistic set."""
    _kernel(
        codes_ref, data_ref, out_ref, nan_ref, pos_ref, neg_ref, comp_ref,
        size_p=size_p, n_tile=n_tile, accum=accum,
    )
    _minmax_accumulate(codes_ref, data_ref, min_ref, size=size, size_p=size_p, op="min")
    _minmax_accumulate(codes_ref, data_ref, max_ref, size=size, size_p=size_p, op="max")


@functools.lru_cache(maxsize=128)
def _build_multistat(
    k_pad: int, n_pad: int, size: int, size_p: int, dtype_str: str, acc_str: str,
    n_tile: int, k_tile: int, interpret: bool, accum: str,
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    kern = functools.partial(
        _multistat_kernel, size=size, size_p=size_p, n_tile=n_tile, accum=accum
    )
    grid = (k_pad // k_tile, n_pad // n_tile)
    acc = jnp.dtype(acc_str)
    dt = jnp.dtype(dtype_str)
    # sums + 3 markers in the accumulator dtype, min/max in the data dtype,
    # then the optional Kahan/double-double compensation block
    out_shape = (
        [jax.ShapeDtypeStruct((size_p, k_pad), acc)] * 4
        + [jax.ShapeDtypeStruct((size_p, k_pad), dt)] * 2
        + ([] if accum == "plain" else [jax.ShapeDtypeStruct((size_p, k_pad), acc)])
    )
    fn = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n_tile), lambda i, j: (0, j)),  # codes
            pl.BlockSpec((k_tile, n_tile), lambda i, j: (i, j)),  # data (K, N)
        ],
        out_specs=[pl.BlockSpec((size_p, k_tile), lambda i, j: (0, i))] * len(out_shape),
        out_shape=out_shape,
        interpret=interpret,
    )
    return jax.jit(fn)


def segment_multistat_pallas(
    data, codes, size: int, *, interpret: bool = False, accum: str | None = None,
):
    """One-pass multi-statistic segment reduction: ``data`` (N, K...) by
    ``codes`` (N,) -> ``(sums, nan_c, pos_c, neg_c, mins, maxs)``, each
    ``(size, K...)``.

    Sums are raw zero-filled compensated totals (apply
    ``utils.reapply_nonfinite`` per skipna mode — one kernel pass serves
    sum AND nansum); min/max are NaN-skipping with empty groups at the
    op's identity (re-inject NaN from ``nan_c`` for the propagating
    variants). Same tiling as ``segment_sum_pallas``, so the sums are
    bit-identical to it; f32/bf16 only.
    """
    import jax.numpy as jnp

    from .options import OPTIONS, VALID_ACCUMS

    if accum is None:
        accum = OPTIONS["pallas_accum"]
    if accum not in VALID_ACCUMS:
        raise ValueError(f"accum must be one of {VALID_ACCUMS}; got {accum!r}")

    data = jnp.asarray(data)
    orig_shape = data.shape
    n = data.shape[0]
    flat = data.reshape(n, -1)
    k = flat.shape[1]
    flat_t = flat.T  # (K, N) — cancels the caller's moveaxis; no copy

    n_tile, k_tile, n_pad, k_pad, size_p = _tiles(n, k, size)

    codes = jnp.asarray(codes).astype(jnp.int32).reshape(-1)
    codes = jnp.where((codes < 0) | (codes >= size), size_p, codes)
    codes_p = jnp.pad(codes, (0, n_pad - n), constant_values=size_p).reshape(1, n_pad)

    from .kernels import _acc_dtype

    fn = _build_multistat(
        k_pad, n_pad, size, size_p, str(flat.dtype),
        str(jnp.dtype(_acc_dtype(flat.dtype))), n_tile, k_tile, interpret,
        str(accum),
    )
    sums, nan_c, pos_c, neg_c, mins, maxs, *_comp = fn(codes_p, flat_t)

    def crop(x):
        return x[:size, :k].reshape((size,) + orig_shape[1:])

    return crop(sums), crop(nan_c), crop(pos_c), crop(neg_c), crop(mins), crop(maxs)


def _probe_card(label: str, compiled, compile_ms: float) -> None:
    """Record the probe executable's analytical card (costmodel plane):
    the probe already holds a ``Compiled`` in hand, so the card costs one
    ``cost_analysis()`` read — no extra compile. No-op when the plane is
    off; never raises (probe contract)."""
    try:
        from . import costmodel

        if costmodel.enabled():
            costmodel.record_compiled(
                label, compiled, compile_ms=compile_ms, sig="probe"
            )
    except Exception:  # noqa: BLE001 — observability never fails a probe
        pass


def probe_compile_multistat() -> None:
    """Compile-only probe for the multi-statistic megakernel (see
    probe_compile)."""
    import time

    import jax
    import jax.numpy as jnp

    from .options import OPTIONS

    fn = _build_multistat(
        128, 128, 2, 8, "float32", "float32", 128, 128, False,
        str(OPTIONS["pallas_accum"]),
    )
    t0 = time.perf_counter()
    compiled = fn.lower(
        jax.ShapeDtypeStruct((1, 128), jnp.int32),
        jax.ShapeDtypeStruct((128, 128), jnp.float32),
    ).compile()
    _probe_card("pallas[multistat]", compiled, (time.perf_counter() - t0) * 1e3)


def _scan_kernel(
    codes_ref, data_ref, out_ref, carry_ref, *marker_refs,
    size_p, n_tile, skipna,
):
    """Grouped cumulative sum, one HBM pass.

    Per tile the grouped prefix is ONE matmul on the MXU:
    ``out = x @ T`` with ``T[l, m] = [l <= m] · [code_l == code_m]`` — the
    triangular-masked group-equality matrix, built in VMEM from the codes
    lane vector (data-independent, shared by every k row). Cross-tile state
    is a per-group running-sum block revisited along the n grid axis, read
    into each lane by a one-hot gather matmul and updated by a one-hot
    contraction — so the cost is independent of the group count (the
    sort-based XLA path this replaces pays an argsort plus a log-depth
    scan, each materialized through HBM).

    Nonfinite handling: ALL nonfinite values (NaN and ±inf) are zero-filled
    before the matmuls — any of them would otherwise poison other groups
    through the masked zeros (inf × 0 = NaN), and undefined edge-block
    garbage with an inf bit pattern would corrupt real outputs. IEEE
    prefix semantics are re-applied from 0/1 seen-marker prefixes computed
    with the same T (DEFAULT precision — exact on 0/1) plus per-group
    marker carry rows: a lane is NaN if its group's prefix saw a NaN
    (non-skipna only) or both +inf and -inf; else ±inf if it saw that
    inf; else the finite sum. The skipna variant (nancumsum) skips only
    the NaN poisoning — inf still propagates, as in ``np.nancumsum`` —
    and carries no NaN-marker row at all. A running group sum that
    OVERFLOWS is folded into the markers and the carry entry reset to 0,
    so the overflowing group reports ±inf from then on while the finite
    carry keeps the gather matmul poison-free.

    Known boundary: overflow detection reflects the MXU contraction's
    reduction order, not the sequential order. Mixed-sign values within a
    tile-width factor of float32 max can make a partial sum overflow where
    the true sequential prefix stays finite (or vice versa) — inherent to
    every reordered summation (pairwise included), not specific to this
    kernel. Data living at that scale belongs on the segmented XLA path
    (``scan_impl="segmented"``) or the x64 CPU engine.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if skipna:
        pcarry_ref, mcarry_ref = marker_refs
        ncarry_ref = None
    else:
        ncarry_ref, pcarry_ref, mcarry_ref = marker_refs

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        carry_ref[:] = jnp.zeros_like(carry_ref)
        if not skipna:
            ncarry_ref[:] = jnp.zeros_like(ncarry_ref)
        pcarry_ref[:] = jnp.zeros_like(pcarry_ref)
        mcarry_ref[:] = jnp.zeros_like(mcarry_ref)

    codes = codes_ref[0, :]  # (n_tile,) — sentinel ``size`` for missing,
    # ``size_p`` for padding (no one-hot column, no T-equality with real lanes)
    data = data_ref[:]  # (k_tile, n_tile)
    acc = carry_ref.dtype
    x = data.astype(acc)
    isnan = jnp.isnan(x)
    ispos = jnp.isposinf(x)
    isneg = jnp.isneginf(x)
    nonfinite = isnan | ispos | isneg
    x = jnp.where(nonfinite, jnp.zeros((), acc), x)

    lane = jax.lax.broadcasted_iota(jnp.int32, (n_tile, n_tile), 0)
    lane_t = jax.lax.broadcasted_iota(jnp.int32, (n_tile, n_tile), 1)
    tri_eq = ((codes[:, None] == codes[None, :]) & (lane <= lane_t)).astype(acc)
    onehot = (
        codes[:, None] == jax.lax.broadcasted_iota(jnp.int32, (n_tile, size_p), 1)
    ).astype(acc)  # (n_tile, size_p)

    hi = jax.lax.Precision.HIGHEST
    # 0/1 marker masks are exact at single-pass precision
    d = jax.lax.Precision.DEFAULT

    def mm(a, b, dims, prec):
        return jax.lax.dot_general(
            a, b, dimension_numbers=(dims, ((), ())),
            preferred_element_type=acc, precision=prec,
        )

    # in-tile grouped prefix + carried-in per-group offset per lane
    prefix = mm(x, tri_eq, ((1,), (0,)), hi)  # (k_tile, n_tile)
    carried = mm(carry_ref[:], onehot, ((0,), (1,)), hi)  # (k_tile, n_tile)
    out = prefix + carried

    def carried_marks():
        # markers seen by this lane's group in EARLIER tiles — gathered
        # before any update below, so this tile's own lanes are untouched
        # by its own value-infs (those enter via tri_eq prefixes below)
        cn = None
        if not skipna:
            cn = mm(ncarry_ref[:], onehot, ((0,), (1,)), d)  # (k_tile, n_tile)
        cp = mm(pcarry_ref[:], onehot, ((0,), (1,)), d)
        cm = mm(mcarry_ref[:], onehot, ((0,), (1,)), d)
        return cn, cp, cm

    def seen_in_tile():
        # value markers at-or-before each lane, plus the group-carry updates
        carried_n, carried_p, carried_m = carried_marks()
        posf = ispos.astype(acc)
        negf = isneg.astype(acc)
        sp = mm(posf, tri_eq, ((1,), (0,)), d) + carried_p
        sm = mm(negf, tri_eq, ((1,), (0,)), d) + carried_m
        pcarry_ref[:] = pcarry_ref[:] + mm(onehot, posf, ((0,), (1,)), d)
        mcarry_ref[:] = mcarry_ref[:] + mm(onehot, negf, ((0,), (1,)), d)
        if skipna:
            return None, sp, sm
        nanf = isnan.astype(acc)
        sn = mm(nanf, tri_eq, ((1,), (0,)), d) + carried_n
        ncarry_ref[:] = ncarry_ref[:] + mm(onehot, nanf, ((0,), (1,)), d)
        return sn, sp, sm

    def finish(seen_n, seen_p, seen_m, with_ovf):
        # IEEE prefix semantics per lane: NaN beats inf; +inf and -inf
        # together make NaN; a lone inf sign wins over any finite sum.
        if with_ovf:
            # Arithmetic OVERFLOW of the zero-filled running sum shows up as
            # ±inf in `out`. An event is genuine only if no value marker has
            # reached its lane (after one, the zero-filled arithmetic is
            # meaningless: a true ±inf running sum absorbs finite addends
            # and cannot re-overflow) AND no opposite-sign overflow happened
            # earlier in the tile (first sign wins, same absorb principle —
            # the cross-tile analogue is `nonfin` in _fold_overflow).
            # Genuine events feed the group markers so later tiles see them,
            # and stick to later in-tile lanes via tri_eq.
            seen_any = seen_p + seen_m
            if seen_n is not None:
                seen_any = seen_any + seen_n
            fresh = seen_any == 0
            o_p_raw = (fresh & jnp.isposinf(out)).astype(acc)
            o_m_raw = (fresh & jnp.isneginf(out)).astype(acc)
            # the prefix matmul's tree reduction can emit NaN directly
            # (opposite-sign inf partials from mixed-sign values near f32
            # max, with no inf lane): a first-class overflow event — else
            # the lane shows a transient NaN that later tiles silently
            # revert, breaking the sticky-group-state model (ADVICE r3)
            o_n_raw = (fresh & jnp.isnan(out)).astype(acc)
            s_p_raw = mm(o_p_raw, tri_eq, ((1,), (0,)), d)
            s_m_raw = mm(o_m_raw, tri_eq, ((1,), (0,)), d)
            s_n_raw = mm(o_n_raw, tri_eq, ((1,), (0,)), d)
            # first event wins per group (absorb principle); a lane never
            # suppresses itself because each lane is exactly one of
            # +inf / -inf / NaN
            o_p = o_p_raw * ((s_m_raw == 0) & (s_n_raw == 0)).astype(acc)
            o_m = o_m_raw * ((s_p_raw == 0) & (s_n_raw == 0)).astype(acc)
            o_n = o_n_raw * ((s_p_raw == 0) & (s_m_raw == 0)).astype(acc)
            if seen_n is None:
                # skipna carries no NaN row: degrade the NaN event to a
                # both-sign marker, mirroring raw_nan in _fold_overflow
                o_p = o_p + o_n
                o_m = o_m + o_n
            pcarry_ref[:] = pcarry_ref[:] + mm(onehot, o_p, ((0,), (1,)), d)
            mcarry_ref[:] = mcarry_ref[:] + mm(onehot, o_m, ((0,), (1,)), d)
            seen_p = seen_p + mm(o_p, tri_eq, ((1,), (0,)), d)
            seen_m = seen_m + mm(o_m, tri_eq, ((1,), (0,)), d)
            if seen_n is not None:
                ncarry_ref[:] = ncarry_ref[:] + mm(onehot, o_n, ((0,), (1,)), d)
                seen_n = seen_n + mm(o_n, tri_eq, ((1,), (0,)), d)
        nan_mask = (seen_p > 0) & (seen_m > 0)
        if seen_n is not None:
            nan_mask = nan_mask | (seen_n > 0)
        res = jnp.where(seen_p > 0, jnp.asarray(jnp.inf, acc), out)
        res = jnp.where(seen_m > 0, jnp.asarray(-jnp.inf, acc), res)
        res = jnp.where(nan_mask, jnp.asarray(jnp.nan, acc), res)
        out_ref[:] = res.astype(out_ref.dtype)

    # Flattened branch matrix (no nested conds — keeps the Mosaic control
    # flow at the shape already proven on hardware). The common clean tile
    # (no nonfinite values, no overflow, no marker ever recorded — checked
    # by a cheap VPU any-reduce over the tiny carry blocks) writes the sums
    # directly and pays zero marker matmuls.
    has_nf = jnp.any(nonfinite)
    # ~isfinite, not isinf: the prefix matmul's tree reduction can produce
    # NaN with no inf lane; such a tile must take an overflow branch so
    # finish() records the event instead of _clean emitting a transient NaN
    has_oinf = jnp.any(~jnp.isfinite(out))
    has_marks = jnp.any(pcarry_ref[:] > 0) | jnp.any(mcarry_ref[:] > 0)
    if not skipna:
        has_marks = has_marks | jnp.any(ncarry_ref[:] > 0)

    @pl.when(~has_nf & ~has_oinf & ~has_marks)
    def _clean():
        out_ref[:] = out.astype(out_ref.dtype)

    @pl.when(~has_nf & ~has_oinf & has_marks)
    def _marked():
        finish(*carried_marks(), False)

    @pl.when(~has_nf & has_oinf)
    def _ovf_only():
        finish(*carried_marks(), True)

    @pl.when(has_nf & ~has_oinf)
    def _vals_only():
        finish(*seen_in_tile(), False)

    @pl.when(has_nf & has_oinf)
    def _vals_ovf():
        finish(*seen_in_tile(), True)

    # New running totals: old carry + this tile's per-group sums. Both
    # addends are finite, but the sum can OVERFLOW — to ±inf, or even to
    # NaN when the matmul's tree reduction forms opposite-sign inf partials
    # from mixed-sign large values. Any nonfinite carry entry would poison
    # every group on the next tile's gather (nonfinite × one-hot 0 = NaN).
    # Keep the carry finite; backstop-record the event as a marker for
    # groups with no nonfinite state yet (an overflow after any marker —
    # including a reset-carry re-overflow — is an artifact: the group's
    # true state is already ±inf/NaN and absorbs finite addends).
    new_carry = carry_ref[:] + mm(onehot, x, ((0,), (1,)), hi)
    raw_p = jnp.isposinf(new_carry)
    raw_m = jnp.isneginf(new_carry)
    raw_nonfin = ~jnp.isfinite(new_carry)
    raw_nan = raw_nonfin & ~raw_p & ~raw_m

    @pl.when(jnp.any(raw_nonfin))
    def _fold_overflow():
        nonfin = (pcarry_ref[:] > 0) | (mcarry_ref[:] > 0)
        if not skipna:
            nonfin = nonfin | (ncarry_ref[:] > 0)
        pcarry_ref[:] = pcarry_ref[:] + (raw_p & ~nonfin).astype(acc)
        mcarry_ref[:] = mcarry_ref[:] + (raw_m & ~nonfin).astype(acc)
        if skipna:
            # no NaN row to record into: a tree-reduction NaN (order-lost
            # mixed-sign overflow) degrades to NaN via both inf markers
            pcarry_ref[:] = pcarry_ref[:] + (raw_nan & ~nonfin).astype(acc)
            mcarry_ref[:] = mcarry_ref[:] + (raw_nan & ~nonfin).astype(acc)
        else:
            ncarry_ref[:] = ncarry_ref[:] + (raw_nan & ~nonfin).astype(acc)

    carry_ref[:] = jnp.where(raw_nonfin, jnp.zeros((), acc), new_carry)


@functools.lru_cache(maxsize=128)
def _build_scan(
    k: int, n: int, n_pad: int, size_p: int, dtype_str: str, acc_str: str,
    n_tile: int, k_tile: int, interpret: bool, skipna: bool,
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    kern = functools.partial(
        _scan_kernel, size_p=size_p, n_tile=n_tile, skipna=skipna
    )
    k_tiles = -(-k // k_tile)
    grid = (k_tiles, n_pad // n_tile)
    acc = jnp.dtype(acc_str)
    fn = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n_tile), lambda i, j: (0, j)),  # codes
            pl.BlockSpec((k_tile, n_tile), lambda i, j: (i, j)),  # data (K, N)
        ],
        out_specs=[
            pl.BlockSpec((k_tile, n_tile), lambda i, j: (i, j)),  # out (K, N)
        ]
        # carry + marker carries: ±inf always, NaN only when it can poison
        + [pl.BlockSpec((size_p, k_tile), lambda i, j: (0, i))] * (3 if skipna else 4),
        out_shape=[jax.ShapeDtypeStruct((k, n), jnp.dtype(dtype_str))]
        + [jax.ShapeDtypeStruct((size_p, k_tiles * k_tile), acc)] * (3 if skipna else 4),
        interpret=interpret,
    )
    return jax.jit(fn)


def segment_cumsum_pallas(data, codes, size: int, *, skipna: bool, interpret: bool = False):
    """Grouped cumulative sum of ``data`` (N, K...) by ``codes`` (N,), same
    shape out. Missing labels (code outside [0, size)) scan among themselves
    as one extra group — matching the sort-based kernel. f32/bf16; bf16
    accumulates in f32 and is cast back per element."""
    import jax.numpy as jnp

    data = jnp.asarray(data)
    orig_shape = data.shape
    n = data.shape[0]
    flat = data.reshape(n, -1)
    k = flat.shape[1]
    flat_t = flat.T  # (K, N) — cancels the caller's moveaxis; no copy

    # one extra carry row for the missing-label group (sentinel == size)
    n_tile, k_tile, n_pad, _k_pad, size_p = _tiles(n, k, size + 1)

    codes = jnp.asarray(codes).astype(jnp.int32).reshape(-1)
    codes = jnp.where((codes < 0) | (codes >= size), size, codes)
    codes_p = jnp.pad(codes, (0, n_pad - n), constant_values=size_p).reshape(1, n_pad)

    from .kernels import _acc_dtype

    fn = _build_scan(
        k, n, n_pad, size_p, str(flat.dtype), str(jnp.dtype(_acc_dtype(flat.dtype))),
        n_tile, k_tile, interpret, bool(skipna),
    )
    out, *_carries = fn(codes_p, flat_t)
    return out.T.reshape(orig_shape)


def probe_compile_scan() -> None:
    """Compile-only probe for the scan kernel (see probe_compile)."""
    import time

    import jax
    import jax.numpy as jnp

    fn = _build_scan(128, 128, 128, 8, "float32", "float32", 128, 128, False, False)
    t0 = time.perf_counter()
    compiled = fn.lower(
        jax.ShapeDtypeStruct((1, 128), jnp.int32),
        jax.ShapeDtypeStruct((128, 128), jnp.float32),
    ).compile()
    _probe_card("pallas[scan]", compiled, (time.perf_counter() - t0) * 1e3)


def probe_compile_minmax() -> None:
    """Compile-only probe for the min/max kernel (see probe_compile)."""
    import time

    import jax
    import jax.numpy as jnp

    fn = _build_minmax(128, 128, 2, 8, "float32", 128, 128, False, "max")
    t0 = time.perf_counter()
    compiled = fn.lower(
        jax.ShapeDtypeStruct((1, 128), jnp.int32),
        jax.ShapeDtypeStruct((128, 128), jnp.float32),
    ).compile()
    _probe_card("pallas[minmax]", compiled, (time.perf_counter() - t0) * 1e3)


def probe_compile() -> None:
    """Lower + compile a tiny instance of the kernel on the real backend
    WITHOUT executing it — safe to call while an outer jit is tracing
    (no concrete arrays are created, so nothing can leak a tracer)."""
    import time

    import jax
    import jax.numpy as jnp

    from .options import OPTIONS

    fn = _build(
        128, 128, 8, "float32", "float32", 128, 128, False,
        str(OPTIONS["pallas_accum"]),
    )
    t0 = time.perf_counter()
    compiled = fn.lower(
        jax.ShapeDtypeStruct((1, 128), jnp.int32),
        jax.ShapeDtypeStruct((128, 128), jnp.float32),
    ).compile()
    _probe_card("pallas[segment_sum]", compiled, (time.perf_counter() - t0) * 1e3)


def segment_sum_pallas(
    data, codes, size: int, *, interpret: bool = False, accum: str | None = None,
    skipna: bool = False, return_nan_counts: bool = False,
):
    """Segment-sum ``data`` (N, K...) by ``codes`` (N,) -> (size, K...).

    Exact IEEE semantics (NaN/±inf propagate per group+column); missing
    labels (code outside [0, size)) drop out. f32/bf16 only. bf16 data
    accumulates — and returns — f32 (the MXU's native accumulate mode;
    see kernels._acc_dtype). ``accum`` (default: the ``pallas_accum``
    option) selects the cross-tile accumulation discipline: "plain",
    "kahan" (compensated), or "dd" (double-double with Dekker-split
    contractions — the strict-accuracy mode chasing the f64 oracle).

    The (N, K) logical view is consumed through its (K, N) transpose so a
    caller-side ``moveaxis(-1, 0)`` cancels and the kernel streams the
    original HBM buffer with no transposed copy.
    """
    sums, nan_c, pos_c, neg_c = segment_sum_raw_pallas(
        data, codes, size, interpret=interpret, accum=accum
    )
    from .utils import reapply_nonfinite

    out = reapply_nonfinite(sums, nan_c, pos_c, neg_c, skipna=skipna)
    if return_nan_counts:
        return out, nan_c
    return out


def segment_sum_raw_pallas(
    data, codes, size: int, *, interpret: bool = False, accum: str | None = None,
):
    """The kernel pass of :func:`segment_sum_pallas` without the IEEE
    re-application: raw zero-filled compensated sums plus the NaN/±inf
    marker counts, each ``(size, K...)`` — one pass can serve both the
    sum and nansum legs of a fused multi-statistic plan."""
    import jax.numpy as jnp

    from .options import OPTIONS, VALID_ACCUMS

    if accum is None:
        accum = OPTIONS["pallas_accum"]
    if accum not in VALID_ACCUMS:
        # same whitelist as the set_options validator: a typo like "khan"
        # must not silently select plain accumulation at lower accuracy
        raise ValueError(f"accum must be one of {VALID_ACCUMS}; got {accum!r}")

    data = jnp.asarray(data)
    orig_shape = data.shape
    n = data.shape[0]
    flat = data.reshape(n, -1)
    k = flat.shape[1]
    flat_t = flat.T  # (K, N) — cancels the caller's moveaxis; no copy

    n_tile, k_tile, n_pad, k_pad, size_p = _tiles(n, k, size)

    codes = jnp.asarray(codes).astype(jnp.int32).reshape(-1)
    # out-of-range codes (missing labels, padding) match no one-hot column
    codes = jnp.where((codes < 0) | (codes >= size), size_p, codes)
    codes_p = jnp.pad(codes, (0, n_pad - n), constant_values=size_p).reshape(1, n_pad)

    from .kernels import _acc_dtype

    # cache key uses k_pad: the program depends only on the tile grid, not
    # the exact trailing size (that enters via the final [:k] slice below)
    fn = _build(
        k_pad, n_pad, size_p, str(flat.dtype), str(jnp.dtype(_acc_dtype(flat.dtype))),
        n_tile, k_tile, interpret, str(accum),
    )
    sums, nan_c, pos_c, neg_c, *_comp = fn(codes_p, flat_t)

    def crop(x):
        return x[:size, :k].reshape((size,) + orig_shape[1:])

    return crop(sums), crop(nan_c), crop(pos_c), crop(neg_c)


# ---------------------------------------------------------------------------
# radix-binning segment sum: the high-cardinality sibling of the kernel
# above. The dense megakernel holds ONE (size_p, k_tile) accumulator block
# in VMEM, which caps it at ~pallas_num_groups_max groups; here the group
# axis is partitioned into g_tile-wide blocks and the grid walks
# (k_tiles, g_blocks, n_tiles) — each (g, i) accumulator tile stays
# VMEM-resident across its whole n sweep and is written back to HBM exactly
# once per pass, so VMEM holds only (n_tile x g_tile) one-hot +
# (g_tile, k_tile) accumulator blocks regardless of the group count.
#
# Intended input is the sort engine's compact domain with rows SORTED by
# code (kernels.sort_segment_reduce's binning pass): each data tile then
# intersects exactly one group block, every other (g, j) step skips the
# MXU contraction on a scalar branch, and consecutive skipped steps cost
# only the tile DMA. Unsorted input stays correct (out-of-block codes
# contract against zero one-hot rows) but pays the full g_blocks x MXU
# sweep.
# ---------------------------------------------------------------------------


def _radixbin_kernel(
    codes_ref, data_ref, out_ref, nan_ref, pos_ref, neg_ref, comp_ref=None,
    *, g_tile, n_tile, accum,
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    g = pl.program_id(1)  # group-block position
    j = pl.program_id(2)  # position along the reduced (N) axis

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)
        nan_ref[:] = jnp.zeros_like(nan_ref)
        pos_ref[:] = jnp.zeros_like(pos_ref)
        neg_ref[:] = jnp.zeros_like(neg_ref)
        if accum != "plain":
            comp_ref[:] = jnp.zeros_like(comp_ref)

    local = codes_ref[0, :] - g * g_tile  # (n_tile,) block-local codes
    inblock = (local >= 0) & (local < g_tile)
    data = data_ref[:]  # (k_tile, n_tile)

    @pl.when(jnp.any(inblock))
    def _contribute():
        # sentinel g_tile matches no one-hot column: out-of-block rows (and
        # the caller's missing/pad sentinel) contract to exactly 0.0
        codes = jnp.where(inblock, local, g_tile)
        onehot = (
            codes[:, None]
            == jax.lax.broadcasted_iota(jnp.int32, (n_tile, g_tile), 1)
        ).astype(data.dtype)  # (n_tile, g_tile) — lives only in VMEM

        isnan = jnp.isnan(data)
        ispos = jnp.isposinf(data)
        isneg = jnp.isneginf(data)
        nonfinite = isnan | ispos | isneg
        zeroed = jnp.where(nonfinite, jnp.zeros((), data.dtype), data)

        def contract(tile, precision):
            return jax.lax.dot_general(
                onehot,
                tile,
                dimension_numbers=(((0,), (1,)), ((), ())),
                preferred_element_type=out_ref.dtype,
                precision=precision,
            )

        _accum_update(out_ref, comp_ref, onehot, zeroed, contract, accum)

        # same two marker savings as the dense kernel, with the gate
        # narrowed to non-finite values that actually fall in this block
        @pl.when(jnp.any(nonfinite & inblock[None, :]))
        def _markers():
            d = jax.lax.Precision.DEFAULT
            nan_ref[:] += contract(isnan.astype(data.dtype), d)
            pos_ref[:] += contract(ispos.astype(data.dtype), d)
            neg_ref[:] += contract(isneg.astype(data.dtype), d)


@functools.lru_cache(maxsize=128)
def _build_radixbin(
    k_pad: int, n_pad: int, size_p: int, g_tile: int, dtype_str: str,
    acc_str: str, n_tile: int, k_tile: int, interpret: bool, accum: str,
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    kern = functools.partial(
        _radixbin_kernel, g_tile=g_tile, n_tile=n_tile, accum=accum
    )
    grid = (k_pad // k_tile, size_p // g_tile, n_pad // n_tile)
    acc = jnp.dtype(acc_str)
    n_out = 4 if accum == "plain" else 5
    out_shape = [jax.ShapeDtypeStruct((size_p, k_pad), acc)] * n_out

    fn = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n_tile), lambda i, g, j: (0, j)),  # codes
            pl.BlockSpec((k_tile, n_tile), lambda i, g, j: (i, j)),  # data (K, N)
        ],
        out_specs=[pl.BlockSpec((g_tile, k_tile), lambda i, g, j: (g, i))] * n_out,
        out_shape=out_shape,
        interpret=interpret,
    )
    return jax.jit(fn)


#: group-block width: lane-width multiple for the one-hot's minor axis and
#: sublane multiple for the accumulator block — one 512-wide block holds
#: the whole dense-kernel regime, more blocks scale the group axis
_RADIXBIN_G_TILE = 512


def segment_sum_radixbin_pallas(
    data, codes, size: int, *, interpret: bool = False, accum: str | None = None,
    skipna: bool = False,
):
    """Segment-sum ``data`` (N, K...) by ``codes`` (N,) -> (size, K...) via
    the radix-binning blocked grid (see the section comment above): exact
    IEEE semantics and accumulation disciplines identical to
    :func:`segment_sum_pallas`, with the group count bounded by the
    ``segment_sum_radixbin_num_groups_max`` option instead of VMEM."""
    import jax.numpy as jnp

    from .options import OPTIONS, VALID_ACCUMS

    if accum is None:
        accum = OPTIONS["pallas_accum"]
    if accum not in VALID_ACCUMS:
        raise ValueError(f"accum must be one of {VALID_ACCUMS}; got {accum!r}")

    data = jnp.asarray(data)
    orig_shape = data.shape
    n = data.shape[0]
    flat = data.reshape(n, -1)
    k = flat.shape[1]
    flat_t = flat.T  # (K, N) — cancels the caller's moveaxis; no copy

    n_tile, k_tile, n_pad, k_pad, _ = _tiles(n, k, size)
    g_tile = min(_RADIXBIN_G_TILE, max(8, ((size + 7) // 8) * 8))
    size_p = -(-size // g_tile) * g_tile

    codes = jnp.asarray(codes).astype(jnp.int32).reshape(-1)
    # out-of-range codes (missing labels, padding) fall outside every block
    codes = jnp.where((codes < 0) | (codes >= size), size_p, codes)
    codes_p = jnp.pad(codes, (0, n_pad - n), constant_values=size_p).reshape(1, n_pad)

    from .kernels import _acc_dtype

    fn = _build_radixbin(
        k_pad, n_pad, size_p, g_tile, str(flat.dtype),
        str(jnp.dtype(_acc_dtype(flat.dtype))), n_tile, k_tile, interpret,
        str(accum),
    )
    sums, nan_c, pos_c, neg_c, *_comp = fn(codes_p, flat_t)

    def crop(x):
        return x[:size, :k].reshape((size,) + orig_shape[1:])

    from .utils import reapply_nonfinite

    return reapply_nonfinite(
        crop(sums), crop(nan_c), crop(pos_c), crop(neg_c), skipna=skipna
    )


def probe_compile_radixbin() -> None:
    """Compile-only probe for the radix-binning kernel (see probe_compile)."""
    import time

    import jax
    import jax.numpy as jnp

    from .options import OPTIONS

    fn = _build_radixbin(
        128, 128, 16, 8, "float32", "float32", 128, 128, False,
        str(OPTIONS["pallas_accum"]),
    )
    t0 = time.perf_counter()
    compiled = fn.lower(
        jax.ShapeDtypeStruct((1, 128), jnp.int32),
        jax.ShapeDtypeStruct((128, 128), jnp.float32),
    ).compile()
    _probe_card("radixbin[segment_sum]", compiled, (time.perf_counter() - t0) * 1e3)
