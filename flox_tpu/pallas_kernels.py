"""Pallas TPU kernels for the hot segment reductions (L1, below kernels.py).

Why a custom kernel: XLA lowers ``segment_sum`` to scatter-add, which
serializes on the VPU; the one-hot GEMM path (kernels._seg_matmul_sum) rides
the MXU but pays 4× HBM traffic for its exactness marker columns. This
kernel gets both: the data streams HBM→VMEM exactly once, and each tile's
contribution is an **in-VMEM** one-hot matmul on the MXU — the one-hot and
the marker masks never touch HBM.

Layout: ``data`` (N, K) reduced over N into (size, K); grid = (k_tiles,
n_tiles) with the output block revisited across the n axis (sequential TPU
grid → accumulate with an init at n==0, the standard reduction pattern).
Non-finite values are zero-filled in VMEM and NaN/±inf markers accumulate in
three extra outputs so IEEE propagation is re-applied exactly.

Reference analogue: the numpy_groupies bincount kernels this replaces
(aggregate_npg.py:7-126) — but tiled for the memory hierarchy the guide
describes (pallas_guide.md: HBM→VMEM→MXU).
"""

from __future__ import annotations

import functools

import numpy as np

__all__ = ["segment_sum_pallas", "pallas_available"]


def pallas_available() -> bool:
    try:
        import jax.experimental.pallas  # noqa: F401

        return True
    except ImportError:  # pragma: no cover
        return False


def _kernel(
    codes_ref, data_ref, out_ref, nan_ref, pos_ref, neg_ref, comp_ref=None,
    *, size_p, n_tile, compensated,
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    j = pl.program_id(1)  # position along the reduced (N) axis

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)
        nan_ref[:] = jnp.zeros_like(nan_ref)
        pos_ref[:] = jnp.zeros_like(pos_ref)
        neg_ref[:] = jnp.zeros_like(neg_ref)
        if compensated:
            comp_ref[:] = jnp.zeros_like(comp_ref)

    codes = codes_ref[0, :]  # (n_tile,)
    data = data_ref[:]  # (n_tile, k_tile)
    onehot = (
        codes[:, None] == jax.lax.broadcasted_iota(jnp.int32, (n_tile, size_p), 1)
    ).astype(data.dtype)  # (n_tile, size_p) — lives only in VMEM

    isnan = jnp.isnan(data)
    ispos = jnp.isposinf(data)
    isneg = jnp.isneginf(data)
    zeroed = jnp.where(isnan | ispos | isneg, jnp.zeros((), data.dtype), data)

    def contract(tile):
        return jax.lax.dot_general(
            onehot,
            tile,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=out_ref.dtype,
            precision=jax.lax.Precision.HIGHEST,
        )

    if compensated:
        # Kahan summation across the sequential n-grid: recovers most of the
        # bits a plain f32 running sum loses over many tiles — the accuracy
        # story on TPUs, where float64 hardware does not exist (the eager
        # CPU path gets true f64 via jax_enable_x64 instead).
        y = contract(zeroed) - comp_ref[:]
        t = out_ref[:] + y
        comp_ref[:] = (t - out_ref[:]) - y
        out_ref[:] = t
    else:
        out_ref[:] += contract(zeroed)
    nan_ref[:] += contract(isnan.astype(data.dtype))
    pos_ref[:] += contract(ispos.astype(data.dtype))
    neg_ref[:] += contract(isneg.astype(data.dtype))


@functools.lru_cache(maxsize=128)
def _build(
    n_pad: int, k_pad: int, size_p: int, dtype_str: str, acc_str: str, n_tile: int,
    k_tile: int, interpret: bool, compensated: bool,
):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    kern = functools.partial(_kernel, size_p=size_p, n_tile=n_tile, compensated=compensated)
    grid = (k_pad // k_tile, n_pad // n_tile)
    # Accumulator blocks are ``acc_str`` (f32 for bf16 data): the data tile
    # streams HBM→VMEM at its narrow width and the MXU contracts bf16×bf16
    # into f32 natively — a bf16 running sum would saturate at 256.
    acc = jnp.dtype(acc_str)
    # the Kahan compensation term rides as a 5th output block (revisited per
    # k-tile like the sums); pallas scratch does not persist across the k
    # grid axis, an output block does. Uncompensated builds skip it entirely.
    n_out = 5 if compensated else 4
    out_shape = [jax.ShapeDtypeStruct((size_p, k_pad), acc)] * n_out

    fn = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, n_tile), lambda i, j: (0, j)),  # codes
            pl.BlockSpec((n_tile, k_tile), lambda i, j: (j, i)),  # data
        ],
        out_specs=[pl.BlockSpec((size_p, k_tile), lambda i, j: (0, i))] * n_out,
        out_shape=out_shape,
        interpret=interpret,
    )
    return jax.jit(fn)


def segment_sum_pallas(
    data, codes, size: int, *, interpret: bool = False, compensated: bool | None = None,
    skipna: bool = False, return_nan_counts: bool = False,
):
    """Segment-sum ``data`` (N, K...) by ``codes`` (N,) -> (size, K...).

    Exact IEEE semantics (NaN/±inf propagate per group+column); missing
    labels (code outside [0, size)) drop out. f32/bf16 only. bf16 data
    accumulates — and returns — f32 (the MXU's native accumulate mode;
    see kernels._acc_dtype). ``compensated`` (default: the
    ``pallas_compensated`` option) applies Kahan summation across tiles.
    """
    import jax.numpy as jnp

    if compensated is None:
        from .options import OPTIONS

        compensated = OPTIONS["pallas_compensated"]

    data = jnp.asarray(data)
    orig_shape = data.shape
    n = data.shape[0]
    flat = data.reshape(n, -1)
    k = flat.shape[1]

    n_tile = 512 if n >= 512 else max(8, ((n + 7) // 8) * 8)
    k_tile = 512 if k >= 512 else max(128, ((k + 127) // 128) * 128)
    n_pad = -(-n // n_tile) * n_tile
    k_pad = -(-k // k_tile) * k_tile
    size_p = max(8, ((size + 7) // 8) * 8)

    codes = jnp.asarray(codes).astype(jnp.int32).reshape(-1)
    # out-of-range codes (missing labels, padding) match no one-hot column
    codes = jnp.where((codes < 0) | (codes >= size), size_p, codes)
    codes_p = jnp.pad(codes, (0, n_pad - n), constant_values=size_p).reshape(1, n_pad)
    flat_p = jnp.pad(flat, ((0, n_pad - n), (0, k_pad - k)))

    from .kernels import _acc_dtype

    fn = _build(
        n_pad, k_pad, size_p, str(flat.dtype), str(jnp.dtype(_acc_dtype(flat.dtype))),
        n_tile, k_tile, interpret, bool(compensated),
    )
    sums, nan_c, pos_c, neg_c, *_comp = fn(codes_p, flat_p)

    from .utils import reapply_nonfinite

    out = reapply_nonfinite(sums, nan_c, pos_c, neg_c, skipna=skipna)
    out = out[:size, :k].reshape((size,) + orig_shape[1:])
    if return_nan_counts:
        return out, nan_c[:size, :k].reshape((size,) + orig_shape[1:])
    return out
