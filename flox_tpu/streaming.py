"""Out-of-core grouped reductions: stream host slabs through device
accumulators (L5).

The reference handles bigger-than-memory arrays by delegating to a chunked
runtime (dask: /root/reference/flox/dask.py:325-573; cubed:
cubed.py:30-162) whose workers each hold one chunk. On a TPU host the
equivalent capability is *streaming*: the array lives in host RAM (or
behind a loader callable — zarr, memmap, a file reader), slabs of the
reduced axis are `device_put` one at a time, and dense per-group
intermediates accumulate **on device** via the same pairwise merges the
mesh runtime applies collectively. HBM holds one slab + the (…, size)
accumulators — never the array.

Design notes (TPU-first):

* The per-slab step is ONE jitted function (chunk kernels + merge fused);
  slabs all share a static shape (the tail slab is padded with ``-1``
  codes), so it compiles once.
* jax dispatch is async: the host can prepare/copy slab ``i+1`` while the
  device reduces slab ``i`` — double buffering without explicit machinery.
* The pairwise variance merge is the reference's ``_var_combine``
  (aggregations.py:392-451) — the Chan update, applied slab-by-slab.
"""

from __future__ import annotations

import math
from typing import Any, Callable

import numpy as np

from . import factorize as fct, utils
from .aggregations import Aggregation, _initialize_aggregation
from .multiarray import MultiArray

__all__ = ["streaming_groupby_reduce"]

_BIG = np.iinfo(np.int32).max


def streaming_groupby_reduce(
    array,
    by,
    *,
    func: str | Aggregation,
    batch_len: int | None = None,
    batch_bytes: int = 256 * 2**20,
    expected_groups=None,
    isbin=False,
    sort: bool = True,
    axis=None,
    fill_value=None,
    dtype=None,
    min_count: int | None = None,
    finalize_kwargs: dict | None = None,
):
    """Grouped reduction streaming slabs to device.

    ``array``: a host array ``(..., *by.shape)`` **or** a loader
    ``callable(start, stop) -> np.ndarray`` returning ``(..., stop-start)``
    slabs (zarr/memmap-style); with a loader, pass 1-D full-axis labels in
    ``by`` — its length defines ``N``. Returns ``(result, groups)`` exactly
    like :func:`flox_tpu.groupby_reduce`.

    nD ``by`` and partial-axis reductions (``axis=`` a subset of the
    by-span, exactly as in ``groupby_reduce``) are supported for host
    arrays: kept dims fold into disjoint per-row code ranges (the same
    flatten ``core.groupby_reduce`` uses), so the stream still walks one
    flat trailing axis. Loaders define a 1-D axis contract, so they keep
    1-D ``by`` / ``axis=None``.

    Supported: every aggregation with a chunk stage (blockwise-only order
    statistics — median/quantile/mode — need all of a group at once and
    cannot stream; use the mesh blockwise method for those).
    """
    import jax
    import jax.numpy as jnp

    from . import dtypes as dtps

    labels = utils.asarray_host(by)
    keep_by_shape: tuple = ()

    loader: Callable[[int, int], Any]
    if callable(array):
        if labels.ndim != 1:
            raise NotImplementedError(
                "loader inputs define a 1-D (start, stop) axis contract: "
                "pass 1-D labels (pre-flatten nD layouts host-side)"
            )
        if axis is not None:
            raise NotImplementedError("axis= needs a host array, not a loader")
        loader = array
        lead_shape = None  # discovered from the first slab
        bys = [labels]
        red_axes = (0,)
    else:
        arr = np.asarray(array) if not utils.is_jax_array(array) else array
        bndim = labels.ndim
        if arr.shape[arr.ndim - bndim:] != labels.shape:
            raise ValueError(
                f"array trailing dims {arr.shape[arr.ndim - bndim:]} != "
                f"by shape {labels.shape}"
            )
        # -- axis normalization: reduced by-dims must trail — the SAME
        # helper core.groupby_reduce uses, so the flatten contracts cannot
        # drift apart (kept dims fold into disjoint per-row code ranges and
        # the stream walks ONE flat axis)
        from .core import _normalize_reduce_axes

        arr, (labels,), n_keep, bndim = _normalize_reduce_axes(arr, [labels], axis)
        keep_by_shape = labels.shape[:n_keep]
        lead_shape = arr.shape[: arr.ndim - bndim]
        span = int(np.prod(labels.shape)) if labels.size else 0
        arr = arr.reshape(lead_shape + (span,))
        loader = lambda s, e: arr[..., s:e]
        bys = [labels]
        red_axes = tuple(range(n_keep, bndim))
    n = int(np.prod(bys[0].shape))

    # -- host factorize over the full label span (cheap: labels only) ------
    from .core import _convert_expected_groups_to_index, _normalize_expected, _normalize_isbin

    expected = _normalize_expected(expected_groups, 1)
    expected_idx = _convert_expected_groups_to_index(expected, _normalize_isbin(isbin, 1), sort)
    codes, found_groups, grp_shape, ngroups, size, props = fct.factorize_(
        bys, axes=red_axes, expected_groups=expected_idx, sort=sort
    )
    codes = np.asarray(codes).reshape(-1)
    if size == 0:
        raise ValueError("No groups to reduce over (empty expected_groups?)")

    probe = np.asarray(loader(0, 1))  # one probe: dtype AND lead shape
    datetime_dtype = probe.dtype if dtps.is_datetime_like(probe.dtype) else None
    nat = False
    if datetime_dtype is not None and not utils.x64_enabled():
        raise ValueError(
            "datetime/timedelta streaming needs jax_enable_x64 (int64 NaT "
            "sentinels do not survive the int32 downcast)."
        )
    agg = _initialize_aggregation(
        func, dtype,
        probe.dtype if datetime_dtype is None else np.dtype("int64"),
        fill_value, 0 if min_count is None else min_count, finalize_kwargs,
    )
    if datetime_dtype is not None:
        # same dtype round-trips as core.groupby_reduce (core.py:495-541),
        # applied PER SLAB so the conversion streams with the data
        from .core import _NAT_INT

        base_loader = loader
        if agg.preserves_dtype:
            # min/max/first/last: exact int64 view, NaT as the sentinel
            from .aggregations import set_nat_final_fill

            nat = True
            set_nat_final_fill(agg, fill_value)
            loader = lambda s, e: np.asarray(base_loader(s, e)).view("int64")
        elif agg.reduction_type == "argreduce" or agg.name in (
            "count", "len", "any", "all"
        ):
            nat = True
            loader = lambda s, e: np.asarray(base_loader(s, e)).view("int64")
        else:
            # float-returning reductions (mean/var/std/sum): f64 epoch
            # values with NaT -> NaN, rounded back in _astype_final
            def loader(s, e):
                sl = np.asarray(base_loader(s, e)).view("int64")
                f = sl.astype(np.float64)
                f[sl == _NAT_INT] = np.nan
                return f
        # no re-probe: the wrap changes dtype only between 8-byte types
        # (datetime64 -> int64/float64), so lead shape and itemsize — the
        # only things probe feeds — are unchanged, and a zarr/S3 loader
        # should not pay a second remote chunk read
    if agg.blockwise_only:
        raise NotImplementedError(
            f"{agg.name!r} needs whole groups at once and cannot stream; "
            "use groupby_reduce(method='blockwise', mesh=...) after "
            "rechunk.reshard_for_blockwise."
        )
    if (
        n >= _BIG
        and not utils.x64_enabled()
        and (agg.reduction_type == "argreduce" or agg.combine in (("first",), ("last",)))
    ):
        raise ValueError(
            f"position-tracking reductions over {n} elements need int64 "
            "positions; enable jax_enable_x64 (int32 would wrap and collide "
            "with the sentinel)."
        )

    if lead_shape is None:
        lead_shape = probe.shape[:-1]
    itemsize = probe.dtype.itemsize
    row_bytes = int(np.prod(lead_shape, dtype=np.int64)) * itemsize if lead_shape else itemsize
    if batch_len is None:
        batch_len = max(1, min(n, batch_bytes // max(row_bytes, 1)))
    nbatches = math.ceil(n / batch_len)

    skipna = agg.name.startswith("nan") or agg.name == "count"
    count_skipna = skipna or agg.min_count > 0

    if nat:
        from .aggregations import shift_nat_identity_fills

        shift_nat_identity_fills(agg)

    step = _build_step(
        agg, size=size, batch_len=batch_len, count_skipna=count_skipna, nat=nat
    )

    state = None
    for i in range(nbatches):
        s, e = i * batch_len, min((i + 1) * batch_len, n)
        slab = np.asarray(loader(s, e))
        ccodes = codes[s:e]
        pad = batch_len - (e - s)
        if pad:
            slab = np.concatenate(
                [slab, np.zeros(lead_shape + (pad,), slab.dtype)], axis=-1
            )
            ccodes = np.concatenate([ccodes, np.full(pad, -1, dtype=ccodes.dtype)])
        # async dispatch: this queues on device while the host loads slab i+1
        state = step(state, jnp.asarray(slab), jnp.asarray(ccodes), jnp.asarray(np.int64(s)))

    inters, counts = state
    if agg.reduction_type == "argreduce":
        result = inters[1]
    elif agg.finalize is not None:
        result = agg.finalize(*inters, **agg.finalize_kwargs)
    else:
        result = inters[0]

    from .parallel.mapreduce import _apply_final_fill

    result = _apply_final_fill(result, counts, agg)
    from .core import _astype_final, _index_values

    result = _astype_final(result, agg, datetime_dtype)
    # (..., size) -> (..., *keep_by, *groups): kept by-dims ride the group
    # axis as disjoint code ranges (factorize_ offsetting) and unfold here
    out_shape = tuple(lead_shape) + tuple(keep_by_shape) + grp_shape
    if result.shape != out_shape:
        result = result.reshape(out_shape)
    return (result,) + tuple(_index_values(g) for g in found_groups)


def _build_step(agg: Aggregation, *, size: int, batch_len: int, count_skipna: bool,
                nat: bool = False):
    """One jitted step: slab -> chunk intermediates -> merge into state."""
    import jax
    import jax.numpy as jnp

    from .kernels import generic_kernel
    from .parallel.mapreduce import _local_chunk, _local_counts

    arg_of_max = agg.reduction_type == "argreduce" and "max" in str(agg.chunk[1])
    is_last = agg.combine == ("last",)
    is_first = agg.combine == ("first",)
    skipna = agg.name.startswith("nan")
    kw = {"nat": True} if nat else {}

    def slab_stats(slab, ccodes, offset):
        counts = _local_counts(ccodes, slab, size, count_skipna, nat)
        if agg.reduction_type == "argreduce":
            val_f, arg_f = agg.chunk
            val = generic_kernel(
                val_f, ccodes, slab, size=size,
                fill_value=agg.fill_value["intermediate"][0], **kw,
            )
            local_arg = generic_kernel(arg_f, ccodes, slab, size=size, fill_value=-1, **kw)
            gidx = jnp.where(local_arg >= 0, local_arg + offset, -1)
            return [val, gidx], counts
        if is_first or is_last:
            from .parallel.mapreduce import _local_firstlast

            val, pos = _local_firstlast(
                ccodes, slab, size, skipna=skipna,
                last=is_last, nat=nat, offset=offset,
            )
            return [val, pos], counts
        return _local_chunk(agg, ccodes, slab, size, nat), counts

    # NaT marker re-injection applies only to propagating (non-skipna)
    # merges — skipna identity fills were shifted off the sentinel above
    nat_markers = nat and not skipna

    def merge(state, inters, counts):
        acc_inters, acc_counts = state
        out = []
        if agg.reduction_type == "argreduce":
            va, ia = acc_inters
            vb, ib = inters
            better = _argmerge_better(va, vb, arg_of_max)
            tie = vb == va
            if jnp.issubdtype(va.dtype, jnp.floating):
                tie = tie | (jnp.isnan(va) & jnp.isnan(vb))
            if nat_markers:
                # NaT-propagating: a NaT extreme wins over any value (its
                # position is the group's first NaT); both-NaT is already a
                # tie through integer equality
                marker = jnp.asarray(np.iinfo(np.int64).min, va.dtype)
                na_, nb_ = va == marker, vb == marker
                better = (better & ~na_ & ~nb_) | (nb_ & ~na_)
            ia_safe = jnp.where(ia >= 0, ia, _BIG)
            ib_safe = jnp.where(ib >= 0, ib, _BIG)
            idx = jnp.where(better, ib_safe, jnp.where(tie, jnp.minimum(ia_safe, ib_safe), ia_safe))
            out = [jnp.where(better, vb, va), jnp.where(idx < _BIG, idx, -1)]
        elif is_first or is_last:
            va, pa = acc_inters
            vb, pb = inters
            if is_last:
                take_b = (pb >= 0) & ((pa < 0) | (pb > pa))
            else:
                take_b = (pb < _BIG) & ((pa >= _BIG) | (pb < pa))
            out = [jnp.where(take_b, vb, va), jnp.where(take_b, pb, pa)]
        else:
            for a, b, op in zip(acc_inters, inters, agg.combine):
                out.append(_pair_merge(op, a, b, nat=nat_markers))
        return out, acc_counts + counts

    def step(state, slab, ccodes, offset):
        inters, counts = slab_stats(slab, ccodes, offset)
        if state is None:
            return (inters, counts)
        return merge(state, inters, counts)

    jitted = jax.jit(step)

    def run(state, slab, ccodes, offset):
        # first call establishes the state pytree; jit caches both arities
        return jitted(state, slab, ccodes, offset)

    return run


def _argmerge_better(va, vb, arg_of_max: bool):
    import jax.numpy as jnp

    better = (vb > va) if arg_of_max else (vb < va)
    if jnp.issubdtype(va.dtype, jnp.floating):
        # NaN-propagating semantics: a NaN extreme wins over a number
        better = better | (jnp.isnan(vb) & ~jnp.isnan(va))
    return better


def _pair_merge(op, a, b, nat: bool = False):
    """Sequential form of the mesh collectives (parallel/mapreduce.py):
    psum -> add, pmax -> maximum, the var triple -> the Chan update
    (reference _var_combine, aggregations.py:392-451). ``nat`` re-injects
    the NaT marker through min/max exactly as _combine_simple does."""
    import jax.numpy as jnp

    if op in ("max", "min") and nat and jnp.issubdtype(a.dtype, jnp.signedinteger):
        # the signedinteger guard matches _combine_simple
        # (parallel/mapreduce.py): bool intermediates (the 'all'/'any'
        # combines) must NOT compare against the int64 marker — the cast
        # marker is True and would absorb every merge
        m = jnp.maximum(a, b) if op == "max" else jnp.minimum(a, b)
        marker = jnp.asarray(np.iinfo(np.int64).min, a.dtype)
        return jnp.where((a == marker) | (b == marker), marker, m)
    if op == "var":
        m2a, ta, na = a.arrays
        m2b, tb, nb = b.arrays
        nab = na + nb
        tab = ta + tb
        mua = ta / jnp.where(na > 0, na, 1)
        mub = tb / jnp.where(nb > 0, nb, 1)
        muab = tab / jnp.where(nab > 0, nab, 1)
        m2 = m2a + m2b + na * (mua - muab) ** 2 + nb * (mub - muab) ** 2
        return MultiArray((m2, tab, nab))
    if op == "sum":
        return a + b
    if op == "prod":
        return a * b
    if op == "max":
        return jnp.maximum(a, b)
    if op == "min":
        return jnp.minimum(a, b)
    if callable(op):
        # the mesh contract: op(stacked) over the shard axis — here the
        # "shards" are the two accumulation halves; leaf-wise for pytrees
        if isinstance(a, MultiArray):
            return op(
                MultiArray(tuple(jnp.stack([x, y]) for x, y in zip(a.arrays, b.arrays)))
            )
        return op(jnp.stack([a, b]))
    raise NotImplementedError(f"streaming merge for combine op {op!r}")
