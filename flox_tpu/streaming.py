"""Out-of-core grouped reductions and scans: stream host slabs through
device accumulators (L5).

The reference handles bigger-than-memory arrays by delegating to a chunked
runtime (dask: /root/reference/flox/dask.py:325-573; cubed:
cubed.py:30-162) whose workers each hold one chunk. On a TPU host the
equivalent capability is *streaming*: the array lives in host RAM (or
behind a loader callable — zarr, memmap, a file reader), slabs of the
reduced axis are `device_put` one at a time, and dense per-group
intermediates accumulate **on device** via the same pairwise merges the
mesh runtime applies collectively. HBM holds one slab + the (…, size)
accumulators — never the array.

Design notes (TPU-first):

* The per-slab step is ONE jitted function (chunk kernels + merge fused);
  slabs all share a static shape (the tail slab is padded with ``-1``
  codes), so it compiles once.
* Staging is pipelined (flox_tpu/pipeline.py): a bounded prefetch pool
  loads, pads, and ``device_put``\\ s slab ``i+k`` while the device reduces
  slab ``i`` — jax's async dispatch alone hides only *compute* behind the
  inline staging, not the load+stage wall itself. All three entry points
  (reduce, scan, quantile) iterate the same :func:`pipeline.stream_slabs`
  source, single-device and mesh alike; ``OPTIONS["stream_prefetch"]=0``
  restores the synchronous inline loop (bit-identical results either way).
* The jitted steps donate their carry (``pipeline.maybe_donate``) so the
  dense ``(…, size)`` accumulators update in place across slabs, and a
  dispatch throttle (``OPTIONS["stream_dispatch_depth"]``) syncs the carry
  every K steps so in-flight slabs cannot pile up unboundedly in HBM.
* The pairwise variance merge is the reference's ``_var_combine``
  (aggregations.py:392-451) — the Chan update, applied slab-by-slab.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Callable

import numpy as np

from . import cache, factorize as fct, utils
from .aggregations import Aggregation, _initialize_aggregation
from .multiarray import MultiArray

logger = logging.getLogger("flox_tpu.streaming")

__all__ = [
    "streaming_groupby_reduce",
    "streaming_groupby_scan",
    "streaming_groupby_aggregate_many",
]

_BIG = np.iinfo(np.int32).max

#: slab byte budget when the caller passes neither batch_len nor
#: batch_bytes — the only sizing leg the autotuner may adapt (an explicit
#: batch_bytes= is a device-memory cap the tuner never second-guesses)
_DEFAULT_BATCH_BYTES = 256 * 2**20

# compiled step/pass/program functions for every streaming runtime path
# (single-device steps, quantile passes, scan steps, mesh shard_map
# pairs) — a fresh jax.jit object per call would recompile on every
# streaming_groupby_* invocation, so repeat same-shaped calls
# (per-variable pipelines) would pay full retrace. Keys carry the
# semantic identity plus trace_fingerprint() (appended by _step_cached).
# LRU-bounded: a cold key past capacity evicts the single stalest step
# (counted in cache.stats()["evictions"]), never the whole hot set — the
# old wholesale clear-at-256 dropped every hot program under sustained
# mixed-key traffic, exactly the serving workload's shape.
_STEP_CACHE: cache.LRUCache = cache.LRUCache(maxsize=256)


def _mesh_stream_layout(mesh, axis_name, batch_len: int, lead_ndim: int):
    """The ONE place the slab sharding layout is decided: device_put
    shardings and shard_map in_specs must stay byte-identical, and
    batch_len must divide into equal shards — both runtimes (reduce and
    quantile) read this."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .parallel.mapreduce import _norm_axes

    axes = _norm_axes(axis_name, mesh)
    ndev = int(np.prod([mesh.shape[a] for a in axes]))
    batch_len = -(-batch_len // ndev) * ndev  # shards must be equal
    spec_entry = axes if len(axes) > 1 else axes[0]
    sspec = P(*([None] * lead_ndim + [spec_entry]))
    cspec = P(spec_entry)
    return (
        axes, ndev, batch_len, spec_entry, sspec, cspec,
        NamedSharding(mesh, sspec), NamedSharding(mesh, cspec),
    )


def _step_cached(key, build):
    from . import telemetry
    from .options import trace_fingerprint

    key = key + (trace_fingerprint(),)
    fn = _STEP_CACHE.get(key)
    if fn is None:
        telemetry.count("cache.step_misses")
        fn = build()
        # bounded LRU insert: past capacity this evicts ONE stale step
        _STEP_CACHE[key] = fn
    else:
        telemetry.count("cache.step_hits")
    return fn


def streaming_groupby_reduce(
    array: Any,
    by: Any,
    *,
    func: str | Aggregation,
    batch_len: int | None = None,
    batch_bytes: int | None = None,
    expected_groups: Any = None,
    isbin: Any = False,
    sort: bool = True,
    axis: Any = None,
    fill_value: Any = None,
    dtype: Any = None,
    min_count: int | None = None,
    finalize_kwargs: dict | None = None,
    mesh: Any = None,
    axis_name: str | tuple[str, ...] = "data",
    engine: str | None = None,
) -> tuple:
    """Grouped reduction streaming slabs to device.

    ``array``: a host array ``(..., *by.shape)`` **or** a loader
    ``callable(start, stop) -> np.ndarray`` returning ``(..., stop-start)``
    slabs (zarr/memmap-style); with a loader, pass 1-D full-axis labels in
    ``by`` — its length defines ``N``. Returns ``(result, groups)`` exactly
    like :func:`flox_tpu.groupby_reduce`.

    nD ``by`` and partial-axis reductions (``axis=`` a subset of the
    by-span, exactly as in ``groupby_reduce``) are supported for host
    arrays: kept dims fold into disjoint per-row code ranges (the same
    flatten ``core.groupby_reduce`` uses), so the stream still walks one
    flat trailing axis. Loaders define a 1-D axis contract, so they keep
    1-D ``by`` / ``axis=None``.

    Supported: every aggregation with a chunk stage, PLUS exact
    quantile/median — the radix-select bisection consumes only per-group
    counts, which accumulate slab by slab, so order statistics stream in
    ``nbits + 1`` full passes over the loader (33 for f32; an explicit,
    documented IO trade — see :func:`_stream_quantile`). ``mode`` cannot
    stream (run-length structure needs contiguous sorted groups); use the
    mesh blockwise method for it.

    ``mesh=`` composes streaming with the sharded runtime (the
    chunked-runtime × scheduler composition the reference gets from dask,
    /root/reference/flox/dask.py:325-573): every slab is ``device_put``
    sharded over the mesh's ``axis_name`` axes, each device folds its
    shard into its OWN accumulator (zero collectives while streaming —
    jax's async dispatch overlaps host loads with device reduction on all
    chips), and ONE collective combine at the end applies the same
    psum / pmax / two-psum Chan merges the mesh map-reduce program uses.
    Bigger-than-host+HBM arrays therefore stream onto N chips at N× the
    slab bandwidth. Above ``dense_intermediate_bytes_max``, additive
    reductions switch to the blocked owner-by-owner form: per-device
    accumulators are ``(…, size/ndev)`` from the start, so group spaces
    beyond any single device's ceiling stream too (see
    docs/distributed.md).
    """
    from . import telemetry

    if isinstance(func, (tuple, list)):
        # the fused multi-statistic routing lives in the impl, but the
        # single-statistic API boundary must fail loudly, not silently
        # change its (array, groups) return contract to (dict, groups)
        raise TypeError(
            "streaming_groupby_reduce takes one func; pass statistic sets "
            "to streaming_groupby_aggregate_many"
        )
    with telemetry.span(
        "streaming_groupby_reduce",
        func=func if isinstance(func, str) else getattr(func, "name", "custom"),
        mesh=mesh is not None,
    ):
        return _streaming_groupby_reduce_impl(
            array, by, func=func, batch_len=batch_len, batch_bytes=batch_bytes,
            expected_groups=expected_groups, isbin=isbin, sort=sort, axis=axis,
            fill_value=fill_value, dtype=dtype, min_count=min_count,
            finalize_kwargs=finalize_kwargs, mesh=mesh, axis_name=axis_name,
            engine=engine,
        )


def _streaming_groupby_reduce_impl(
    array: Any,
    by: Any,
    *,
    func: str | Aggregation,
    batch_len: int | None,
    batch_bytes: int | None,
    expected_groups: Any,
    isbin: Any,
    sort: bool,
    axis: Any,
    fill_value: Any,
    dtype: Any,
    min_count: int | None,
    finalize_kwargs: dict | None,
    mesh: Any,
    axis_name: str | tuple[str, ...],
    engine: str | None = None,
) -> tuple:
    """The :func:`streaming_groupby_reduce` body, under the public
    wrapper's root telemetry span (per-pass ``stream[...]`` spans come from
    ``pipeline.stream_slabs``; defaults live only on the wrapper)."""
    from . import dtypes as dtps

    labels = utils.asarray_host(by)
    keep_by_shape: tuple = ()

    loader: Callable[[int, int], Any]
    if callable(array):
        if labels.ndim != 1:
            raise NotImplementedError(
                "loader inputs define a 1-D (start, stop) axis contract: "
                "pass 1-D labels (pre-flatten nD layouts host-side)"
            )
        if axis is not None:
            raise NotImplementedError("axis= needs a host array, not a loader")
        loader = array
        lead_shape = None  # discovered from the first slab
        bys = [labels]
        red_axes = (0,)
    else:
        arr = np.asarray(array) if not utils.is_jax_array(array) else array
        bndim = labels.ndim
        if arr.shape[arr.ndim - bndim:] != labels.shape:
            raise ValueError(
                f"array trailing dims {arr.shape[arr.ndim - bndim:]} != "
                f"by shape {labels.shape}"
            )
        # -- axis normalization: reduced by-dims must trail — the SAME
        # helper core.groupby_reduce uses, so the flatten contracts cannot
        # drift apart (kept dims fold into disjoint per-row code ranges and
        # the stream walks ONE flat axis)
        from .core import _normalize_reduce_axes

        arr, (labels,), n_keep, bndim = _normalize_reduce_axes(arr, [labels], axis)
        keep_by_shape = labels.shape[:n_keep]
        lead_shape = arr.shape[: arr.ndim - bndim]
        span = int(np.prod(labels.shape)) if labels.size else 0
        arr = arr.reshape(lead_shape + (span,))
        loader = lambda s, e: arr[..., s:e]
        bys = [labels]
        red_axes = tuple(range(n_keep, bndim))
    n = int(np.prod(bys[0].shape))

    # -- host factorize over the full label span (cheap: labels only) ------
    from .core import _convert_expected_groups_to_index, _normalize_expected, _normalize_isbin

    expected = _normalize_expected(expected_groups, 1)
    expected_idx = _convert_expected_groups_to_index(expected, _normalize_isbin(isbin, 1), sort)
    from . import telemetry

    with telemetry.span("factorize") as _fsp:
        codes, found_groups, grp_shape, ngroups, size, props = fct.factorize_(
            bys, axes=red_axes, expected_groups=expected_idx, sort=sort
        )
        _fsp.set(ngroups=ngroups, size=size)
    # ONE contiguous int32 copy for the whole stream: per-slab slices are
    # then zero-copy contiguous views, so the loop (and the prefetch
    # workers) never re-copy or re-cast codes per slab
    codes = np.ascontiguousarray(np.asarray(codes).reshape(-1), dtype=np.int32)
    if size == 0:
        raise ValueError("No groups to reduce over (empty expected_groups?)")

    probe = np.asarray(loader(0, 1))  # one probe: dtype AND lead shape
    datetime_dtype = probe.dtype if dtps.is_datetime_like(probe.dtype) else None
    nat = False
    if datetime_dtype is not None and not utils.x64_enabled():
        raise ValueError(
            "datetime/timedelta streaming needs jax_enable_x64 (int64 NaT "
            "sentinels do not survive the int32 downcast)."
        )
    fused_funcs = tuple(func) if isinstance(func, (tuple, list)) else None
    if fused_funcs is not None:
        # multi-statistic fusion: ONE streaming pass (one carry, one step
        # program, one checkpoint identity) serves the whole statistic set
        if datetime_dtype is not None:
            raise NotImplementedError(
                "fused multi-statistic streaming supports numeric data; "
                "stream datetime reductions one func at a time"
            )
        from .aggregations import plan_fused

        agg = plan_fused(
            fused_funcs, dtype, probe.dtype, fill_value,
            0 if min_count is None else min_count, finalize_kwargs,
        )
    else:
        agg = _initialize_aggregation(
            func, dtype,
            probe.dtype if datetime_dtype is None else np.dtype("int64"),
            fill_value, 0 if min_count is None else min_count, finalize_kwargs,
        )
        if agg.appended_count:
            # the streaming runtime computes counts itself (count_skipna
            # channel + _apply_final_fill threshold); the appended nanlen
            # would otherwise leak into agg.finalize as a stray positional
            # arg — var's ddof became a count array, poisoning every group
            # (the same strip sharded_groupby_reduce applies)
            agg.chunk = agg.chunk[:-1]
            agg.combine = agg.combine[:-1]
            agg.fill_value["intermediate"] = agg.fill_value["intermediate"][:-1]
            agg.appended_count = False
    if datetime_dtype is not None:
        # same dtype round-trips as core.groupby_reduce (core.py:495-541),
        # applied PER SLAB so the conversion streams with the data
        from .core import _NAT_INT

        base_loader = loader
        if agg.preserves_dtype:
            # min/max/first/last: exact int64 view, NaT as the sentinel
            from .aggregations import set_nat_final_fill

            nat = True
            set_nat_final_fill(agg, fill_value)
            loader = lambda s, e: np.asarray(base_loader(s, e)).view("int64")
        elif agg.reduction_type == "argreduce" or agg.name in (
            "count", "len", "any", "all"
        ):
            nat = True
            loader = lambda s, e: np.asarray(base_loader(s, e)).view("int64")
        else:
            # float-returning reductions (mean/var/std/sum): f64 epoch
            # values with NaT -> NaN, rounded back in _astype_final
            def loader(s, e):
                sl = np.asarray(base_loader(s, e)).view("int64")
                f = sl.astype(np.float64)
                f[sl == _NAT_INT] = np.nan
                return f
        # no re-probe: the wrap changes dtype only between 8-byte types
        # (datetime64 -> int64/float64), so lead shape and itemsize — the
        # only things probe feeds — are unchanged, and a zarr/S3 loader
        # should not pay a second remote chunk read
    stream_orderstat = False
    if agg.blockwise_only:
        if agg.name in ("median", "nanmedian", "quantile", "nanquantile"):
            # quantile/median DO stream: the radix-select bisection only
            # ever needs per-group COUNTS, which accumulate slab by slab —
            # (nbits + 1) full passes over the data (see _stream_quantile).
            # With mesh= each slab is sharded and every counting pass
            # psums — out-of-core AND distributed at once.
            stream_orderstat = True
        else:
            raise NotImplementedError(
                f"{agg.name!r} cannot stream on this path; use "
                "groupby_reduce(method='blockwise', mesh=...) after "
                "rechunk.reshard_for_blockwise."
            )
    if (
        n >= _BIG
        and not utils.x64_enabled()
        and (agg.reduction_type == "argreduce" or agg.combine in (("first",), ("last",)))
    ):
        raise ValueError(
            f"position-tracking reductions over {n} elements need int64 "
            "positions; enable jax_enable_x64 (int32 would wrap and collide "
            "with the sentinel)."
        )

    if lead_shape is None:
        lead_shape = probe.shape[:-1]
    itemsize = probe.dtype.itemsize
    row_bytes = int(np.prod(lead_shape, dtype=np.int64)) * itemsize if lead_shape else itemsize

    # -- present-groups (sort) engine: compact once for the WHOLE stream ---
    # The stream's codes are host-known upfront, so the union of groups any
    # slab can touch is known before the first slab stages: compact the
    # code span once and the carry — through every step program, OOM
    # split, checkpoint snapshot and the mesh collectives — is sized by
    # the groups present in the stream, not the label universe. A resumed
    # process recomputes the identical present table from the identical
    # inputs, so checkpoint identities (which fingerprint the compact
    # codes + capacity) match bit-for-bit across kill/resume.
    present_table = None
    size_full = size
    engine = _route_stream_highcard(
        engine, codes, size, probe, lead_shape, agg, n=n
    )
    if engine == "sort":
        from .core import _note_highcard
        from .kernels import compact_codes, present_cap, present_groups

        present_table = present_groups(codes, size)
        if len(present_table) < size:
            ncap = present_cap(len(present_table), size)
            codes = compact_codes(codes, present_table)
            _note_highcard(size, ncap, len(present_table))
            size = ncap
        else:
            present_table = None  # universe fully present: dense == compact
    if batch_len is None:
        from .options import OPTIONS

        explicit_bytes = batch_bytes is not None
        if not explicit_bytes:
            batch_bytes = _DEFAULT_BATCH_BYTES
        if (
            OPTIONS["autotune"]
            and not explicit_bytes
            and not OPTIONS["stream_checkpoint_path"]
        ):
            # observed-best slab byte budget for this stream-size band
            # (fed by past StreamReport observations); the default budget
            # otherwise. Explicit sizing is never second-guessed — a
            # passed batch_len pins the slab length and a passed
            # batch_bytes is a device-memory cap — only the
            # nothing-specified default adapts. With checkpointing on, the
            # derived batch_len is part of the checkpoint identity key: it
            # must be reproducible by the resuming process, and a store
            # whose winner shifted between runs would silently orphan the
            # snapshot — so adaptation is off whenever a checkpoint path
            # is configured.
            from .autotune import pick_stream_batch_bytes

            lead_elems = int(np.prod(lead_shape, dtype=np.int64)) if lead_shape else 1
            batch_bytes = pick_stream_batch_bytes(
                batch_bytes, nelems=int(n) * lead_elems
            )
        batch_len = max(1, min(n, batch_bytes // max(row_bytes, 1)))

    if stream_orderstat:
        result = _stream_quantile(
            agg, loader, codes, size=size, n=n, batch_len=batch_len,
            lead_shape=tuple(lead_shape), mesh=mesh, axis_name=axis_name,
            # the datetime wrap changes the effective dtype to float64
            probe_dtype=np.float64 if datetime_dtype is not None else probe.dtype,
            data_probe=probe,
        )
        from .core import _astype_final, _index_values

        result = _astype_final(result, agg, datetime_dtype)
        result = _scatter_stream(result, present_table, size_full)
        out_shape = (
            agg.new_dims() + tuple(lead_shape) + tuple(keep_by_shape) + grp_shape
        )
        if result.shape != out_shape:
            result = result.reshape(out_shape)
        return (result,) + tuple(_index_values(g) for g in found_groups)

    skipna = agg.name.startswith("nan") or agg.name == "count"
    count_skipna = skipna or agg.min_count > 0

    if nat:
        from .aggregations import shift_nat_identity_fills

        shift_nat_identity_fills(agg)

    slab_shard = codes_shard = None
    spec_entry = None
    mesh_key = None
    shard_quantum = 1
    if mesh is not None:
        from .options import OPTIONS
        from .parallel.mapreduce import _is_additive, dense_intermediate_bytes
        from .utils import fmt_bytes

        axes, ndev, batch_len, spec_entry, _sspec, _cspec, slab_shard, codes_shard = (
            _mesh_stream_layout(mesh, axis_name, batch_len, len(lead_shape))
        )
        shard_quantum = ndev

        # ceiling routing — the same decision sharded_groupby_reduce makes:
        # per-device accumulators are one dense (..., size) buffer set, so
        # above the ceiling additive aggs switch to owner-blocked
        # accumulation and everything else fails actionably
        lead_elems = int(np.prod(lead_shape, dtype=np.int64)) if lead_shape else 1
        est = dense_intermediate_bytes(lead_elems, size, probe.dtype, agg, ndev)
        ceiling = OPTIONS["dense_intermediate_bytes_max"]
        blocked = False
        if est > ceiling:
            result_bytes = lead_elems * size * max(4, itemsize)
            blocked_est = result_bytes + est // ndev
            if _is_additive(agg) and blocked_est <= ceiling:
                blocked = True
            else:
                how = (
                    "its combine cannot be distributed by group ownership"
                    if not _is_additive(agg)
                    else f"even the blocked owner-by-owner form needs "
                    f"~{fmt_bytes(blocked_est)}/device over {ndev} device(s)"
                )
                raise ValueError(
                    f"streaming {agg.name!r} over {size} groups needs "
                    f"~{fmt_bytes(est)} of dense (..., size) accumulators per "
                    f"device, above the {fmt_bytes(ceiling)} "
                    f"dense_intermediate_bytes_max ceiling, and {how}. Options: "
                    "use engine='sort' (FLOX_TPU_DEFAULT_ENGINE=sort — the "
                    "carry then covers only the groups present in the stream); "
                    "reduce expected_groups; shard over more devices; or raise "
                    "set_options(dense_intermediate_bytes_max=...) if the "
                    "devices really have the headroom."
                )

        # program cache (the _PROGRAM_CACHE pattern from the sharded
        # runtime): repeat same-shaped calls — per-variable streaming over
        # a dataset, pipelines — reuse the three compiled shard_map
        # programs instead of retracing
        from .parallel.mapreduce import _agg_cache_key

        def _build_mesh_pair():
            if blocked:
                size_pad = size + (-size) % ndev
                return (
                    _build_mesh_step_blocked(
                        agg, size_pad=size_pad, ndev=ndev, count_skipna=count_skipna,
                        nat=nat, mesh=mesh, axes=axes, lead_ndim=len(lead_shape),
                    ),
                    _build_mesh_final_blocked(agg, size=size, mesh=mesh, axes=axes),
                )
            return (
                _build_mesh_step(
                    agg, size=size, count_skipna=count_skipna,
                    nat=nat, mesh=mesh, axes=axes, lead_ndim=len(lead_shape),
                ),
                _build_mesh_final(agg, mesh=mesh, axes=axes, nat=nat),
            )

        # no shard_len in the key: the step programs are shape-polymorphic
        # (per-device offsets come from the traced shard width), so streams
        # that differ only in batch_len share one cached (step, final) pair
        step, final = _step_cached(
            ("mesh", _agg_cache_key(agg), size, axes, mesh, nat,
             blocked, len(lead_shape)),
            _build_mesh_pair,
        )
        mesh_key = (tuple(axes), ndev, blocked)
    else:
        from .parallel.mapreduce import _agg_cache_key

        step = _step_cached(
            ("reduce-step", _agg_cache_key(agg), size, count_skipna, nat),
            lambda: _build_step(agg, size=size, count_skipna=count_skipna, nat=nat),
        )
    nbatches = math.ceil(n / batch_len)

    from .pipeline import DispatchThrottle, SlabStager, stream_slabs
    from .profiling import timed
    from .resilience import (
        StreamCheckpointer,
        StreamCounters,
        device_restore,
        dispatch_slab,
    )

    counters = StreamCounters()
    stager = SlabStager(
        loader, codes, n=n, batch_len=batch_len, lead_shape=tuple(lead_shape),
        slab_shard=slab_shard, codes_shard=codes_shard, with_offset=True,
        counters=counters,
    )
    from .parallel.mapreduce import _agg_cache_key

    ckpt = StreamCheckpointer.for_stream(
        # repr(_agg_cache_key) carries the RESOLVED aggregation identity
        # (dtype= override, custom chunk/combine, finalize_kwargs) as a
        # picklable string — a snapshot from a same-named but different
        # aggregation must miss, not silently fold. Custom-callable ids
        # differ across processes, so a cross-process .npz resume of a
        # custom agg misses too: a fresh run, never a mismatched one.
        kind="reduce", name=repr(_agg_cache_key(agg)), n=n, batch_len=batch_len,
        size=size, codes=codes, lead_shape=tuple(lead_shape), mesh_key=mesh_key,
        extra=(nat, count_skipna, str(probe.dtype)), data_probe=probe,
        counters=counters,
    )
    state = None
    skip = 0
    snap = ckpt.restore()
    if snap is not None:
        # bit-identical resume: the carry round-trips host exactly, and the
        # remaining slabs refold in the same stream order
        skip = snap.slabs_done
        state = device_restore(snap.payload, mesh=mesh, spec_entry=spec_entry)
    done = skip
    throttle = DispatchThrottle()

    from . import costmodel

    # the cost-ledger key pipeline.stream_slabs bills this stream under —
    # the card label must match it exactly or the roofline join misses.
    # The step arguments are captured as ShapeDtypeStructs DURING the loop
    # but the card (one lower+compile for analysis) is recorded AFTER it,
    # so the analysis wall never lands in the pass's billed dispatch time.
    stream_prog = f"stream[reduce[{agg.name}]]"
    card_capture: list = []

    def apply_step(st, sb):
        if costmodel.enabled() and len(card_capture) < 2:
            # first slab captures the init program, second the steady-state
            # carry program (the one that dominates a long stream) — mesh
            # runners expose both on _jitted/_jitted_init, the single-device
            # step covers both arities through one jitted function
            if st is None and hasattr(step, "_jitted_init"):
                card_capture.append((
                    step._jitted_init,
                    costmodel.aval_args((sb.data, sb.codes, sb.offset)),
                ))
            else:
                card_capture.append((
                    getattr(step, "_jitted", None),
                    costmodel.aval_args((st, sb.data, sb.codes, sb.offset)),
                ))
        return step(st, sb.data, sb.codes, sb.offset)

    with timed(f"stream [{agg.name}] {nbatches} slab(s) x {batch_len}"):
        # the pipeline stages slab i+k (load, pad, device_put against the
        # shardings above) while the step for slab i runs; the step itself
        # dispatches async, and the throttle syncs the carry every K steps.
        # dispatch_slab adds the fault hook + OOM halve-and-re-stage, and
        # the checkpointer snapshots the carry every K processed slabs.
        for sl in stream_slabs(
            loader, codes, n=n, batch_len=batch_len, lead_shape=tuple(lead_shape),
            slab_shard=slab_shard, codes_shard=codes_shard, with_offset=True,
            label=f"reduce[{agg.name}]", skip=skip, counters=counters, stager=stager,
        ):
            state = dispatch_slab(
                apply_step, state, sl, stager=stager, counters=counters,
                shard_quantum=shard_quantum,
                highcard_hint=_highcard_oom_hint(agg, size, present_table),
            )
            throttle.tick(state)
            done += 1
            ckpt.tick(lambda: state, slabs_done=done)

    if card_capture:
        # steady-state program preferred (the capture list's tail); the
        # analysis compile runs here, outside the stream's timed window
        fn, sds = card_capture[-1]
        costmodel.ensure_card(stream_prog, fn, sds)

    out_shape = tuple(lead_shape) + tuple(keep_by_shape) + grp_shape
    if mesh is not None:
        with telemetry.span("finalize", mesh=True):
            result = final(state)
            ckpt.done()
            from .core import _astype_final, _index_values

            if fused_funcs is not None:
                out = _finalize_many_stream(
                    agg, result, out_shape, present_table, size_full
                )
                return (out,) + tuple(_index_values(g) for g in found_groups)
            result = _astype_final(result, agg, datetime_dtype)
            result = _scatter_stream(result, present_table, size_full)
            if result.shape != out_shape:
                result = result.reshape(out_shape)
        return (result,) + tuple(_index_values(g) for g in found_groups)

    with telemetry.span("finalize"):
        inters, counts = state
        from .parallel.mapreduce import _finalize_combined

        result = _finalize_combined(agg, inters, counts)
        ckpt.done()
        from .core import _astype_final, _index_values

        if fused_funcs is not None:
            # one streaming pass -> the whole statistic set
            out = _finalize_many_stream(
                agg, result, out_shape, present_table, size_full
            )
            return (out,) + tuple(_index_values(g) for g in found_groups)
        result = _astype_final(result, agg, datetime_dtype)
        result = _scatter_stream(result, present_table, size_full)
        # (..., size) -> (..., *keep_by, *groups): kept by-dims ride the group
        # axis as disjoint code ranges (factorize_ offsetting) and unfold here
        if result.shape != out_shape:
            result = result.reshape(out_shape)
    return (result,) + tuple(_index_values(g) for g in found_groups)


def streaming_groupby_aggregate_many(
    array: Any,
    by: Any,
    *,
    funcs: "tuple | list" = ("sum", "count", "min", "max", "var"),
    batch_len: int | None = None,
    batch_bytes: int | None = None,
    expected_groups: Any = None,
    isbin: Any = False,
    sort: bool = True,
    axis: Any = None,
    fill_value: Any = None,
    dtype: Any = None,
    min_count: int | None = None,
    finalize_kwargs: dict | None = None,
    mesh: Any = None,
    axis_name: str | tuple[str, ...] = "data",
    engine: str | None = None,
) -> tuple:
    """N grouped statistics in ONE streaming pass over the loader.

    The multi-statistic form of :func:`streaming_groupby_reduce`: the
    fusion planner (``aggregations.plan_fused``) merges the requested
    statistic blueprints into one multi-output chunk plan, so every slab
    is staged ONCE and folds into one fused carry — an ERA5-style
    mean+std+extremes job is one pass over the data instead of four.
    Checkpoint/resume (the fused carry snapshots under one stream
    identity) and OOM slab-splitting work exactly as for a single
    statistic; ``mesh=`` composes with the sharded runtime (one collective
    combine for the whole set). Returns ``(results, groups)`` with
    ``results`` a dict mapping func name -> array, each bit-identical to
    the corresponding single-statistic streaming call.
    """
    from . import telemetry

    with telemetry.span(
        "streaming_groupby_aggregate_many", funcs=list(funcs),
        mesh=mesh is not None,
    ):
        return _streaming_groupby_reduce_impl(
            array, by, func=tuple(funcs), batch_len=batch_len,
            batch_bytes=batch_bytes, expected_groups=expected_groups,
            isbin=isbin, sort=sort, axis=axis, fill_value=fill_value,
            dtype=dtype, min_count=min_count, finalize_kwargs=finalize_kwargs,
            mesh=mesh, axis_name=axis_name, engine=engine,
        )


def _route_stream_highcard(engine, codes, size, probe, lead_shape, agg, *, n):
    """Dense-vs-sort routing for the streaming runtime — the streaming
    sibling of ``core._route_highcard``. ``engine=None`` auto-routes:
    above ``dense_intermediate_bytes_max`` the sort engine is taken
    whenever its compact domain fits (the carry the ladder could never
    shrink now tracks present groups); between ``sort_engine_min_groups``
    and the ceiling the "highcard" autotune family decides — except when a
    checkpoint path is configured, where routing must be reproducible by
    the resuming process, so only the static heuristic applies (the same
    rule the adaptive slab sizing follows). Explicit engines are never
    second-guessed; "numpy" has no streaming form and is rejected.
    """
    from .options import OPTIONS

    if engine is not None:
        from .aggregations import normalize_engine

        engine = normalize_engine(engine)
        if engine == "numpy":
            raise ValueError(
                "the streaming runtime folds slabs on device; engine='numpy' "
                "has no streaming form (use groupby_reduce on host data)."
            )
        return engine
    from .parallel.mapreduce import dense_intermediate_bytes

    lead_elems = int(np.prod(lead_shape, dtype=np.int64)) if lead_shape else 1
    est = dense_intermediate_bytes(lead_elems, size, probe.dtype, agg, 1)
    ceiling = OPTIONS["dense_intermediate_bytes_max"]
    over = est > ceiling
    if OPTIONS["default_engine"] == "sort":
        return "sort"
    if not over and size < OPTIONS["sort_engine_min_groups"]:
        return "jax"
    from .kernels import present_cap, present_groups

    present = present_groups(codes, size)  # memoized; the sort path reuses it
    ncap = present_cap(len(present), size)
    if over:
        est_sort = dense_intermediate_bytes(lead_elems, ncap, probe.dtype, agg, 1)
        if est_sort <= ceiling:
            from . import telemetry

            telemetry.count("highcard.ceiling_routes")
            logger.debug(
                "stream highcard: dense estimate over ceiling -> sort engine "
                "(size=%d present=%d)", size, len(present),
            )
            return "sort"
        return "jax"  # the mesh blocked program / ceiling error downstream decides
    from .core import _HIGHCARD_DENSITY_DEN

    heuristic = "sort" if ncap * _HIGHCARD_DENSITY_DEN <= size else "dense"
    chosen = heuristic
    if OPTIONS["autotune"] and not OPTIONS["stream_checkpoint_path"]:
        from . import autotune

        nelems = int(n) * lead_elems
        autotune.prime_highcard(probe.dtype, size, len(present), nelems)
        chosen = autotune.decide(
            "highcard", heuristic, ("dense", "sort"),
            dtype=str(probe.dtype), ngroups=size, nelems=nelems,
        )
    return "sort" if chosen == "sort" else "jax"


def _highcard_oom_hint(agg, size: int, present_table) -> str | None:
    """The ngroups-dominated flag for the OOM ladder (see
    ``resilience.dispatch_slab``): set on dense runs whose accumulators
    span a universe past ``sort_engine_min_groups`` — the allocation the
    ladder can never shrink — and never on already-compacted runs."""
    from .options import OPTIONS

    if present_table is not None or size < OPTIONS["sort_engine_min_groups"]:
        return None
    return (
        f"the {agg.name!r} accumulators are dense over the {size}-label "
        "universe, which slab-splitting cannot shrink. The sort "
        "(present-groups) engine accumulates only over groups actually "
        "present: pass engine='sort' (or set FLOX_TPU_DEFAULT_ENGINE=sort), "
        "or lower expected_groups."
    )


def _scatter_stream(result, present_table, size_full: int):
    """Expand a compact streaming result to the dense (..., size) layout
    (host-side; no-op on dense runs)."""
    if present_table is None:
        return result
    from .kernels import scatter_present_dense

    return scatter_present_dense(np.asarray(result), present_table, size_full)


def _finalize_many_stream(agg, result, out_shape, present_table, size_full: int):
    """Fused finalize with the present-groups scatter-back: each statistic
    expands from the compact domain before the (dense) reshape. Dense runs
    take the shared :func:`fusion.finalize_many` unchanged."""
    from .fusion import finalize_many

    if present_table is None:
        return finalize_many(agg, result, out_shape)
    outs = finalize_many(agg, result, None)
    fixed = {}
    for f, v in outs.items():
        v = _scatter_stream(v, present_table, size_full)
        if tuple(v.shape) != tuple(out_shape):
            v = v.reshape(out_shape)
        fixed[f] = v
    return fixed


def _slab_stats(agg: Aggregation, slab, ccodes, offset, *, size: int,
                count_skipna: bool, nat: bool):
    """Chunk intermediates + counts for one slab (or one shard of a slab).
    ``offset`` is the slab's global start position (traced), already
    including the device offset on the mesh path."""
    import jax.numpy as jnp

    from .kernels import generic_kernel
    from .parallel.mapreduce import _local_chunk, _local_counts

    skipna = agg.name.startswith("nan")
    kw = {"nat": True} if nat else {}
    counts = _local_counts(ccodes, slab, size, count_skipna, nat)
    if agg.reduction_type == "argreduce":
        val_f, arg_f = agg.chunk
        val = generic_kernel(
            val_f, ccodes, slab, size=size,
            fill_value=agg.fill_value["intermediate"][0], **kw,
        )
        local_arg = generic_kernel(arg_f, ccodes, slab, size=size, fill_value=-1, **kw)
        gidx = jnp.where(local_arg >= 0, local_arg + offset, -1)
        return [val, gidx], counts
    if agg.combine in (("first",), ("last",)):
        from .parallel.mapreduce import _local_firstlast

        val, pos = _local_firstlast(
            ccodes, slab, size, skipna=skipna,
            last=agg.combine == ("last",), nat=nat, offset=offset,
        )
        return [val, pos], counts
    return _local_chunk(agg, ccodes, slab, size, nat), counts


def _merge_into(agg: Aggregation, state, inters, counts, *, nat: bool):
    """Fold one slab's intermediates into the running state — the
    sequential form of the mesh collectives, shared by the single-device
    and the per-device (mesh) accumulation loops."""
    import jax.numpy as jnp

    skipna = agg.name.startswith("nan")
    # NaT marker re-injection applies only to propagating (non-skipna)
    # merges — skipna identity fills were shifted off the sentinel upstream
    nat_markers = nat and not skipna
    acc_inters, acc_counts = state
    out = []
    if agg.reduction_type == "argreduce":
        arg_of_max = "max" in str(agg.chunk[1])
        va, ia = acc_inters
        vb, ib = inters
        better = _argmerge_better(va, vb, arg_of_max)
        tie = vb == va
        if jnp.issubdtype(va.dtype, jnp.floating):
            tie = tie | (jnp.isnan(va) & jnp.isnan(vb))
        if nat_markers:
            # NaT-propagating: a NaT extreme wins over any value (its
            # position is the group's first NaT); both-NaT is already a
            # tie through integer equality
            marker = jnp.asarray(np.iinfo(np.int64).min, va.dtype)
            na_, nb_ = va == marker, vb == marker
            better = (better & ~na_ & ~nb_) | (nb_ & ~na_)
        ia_safe = jnp.where(ia >= 0, ia, _BIG)
        ib_safe = jnp.where(ib >= 0, ib, _BIG)
        idx = jnp.where(better, ib_safe, jnp.where(tie, jnp.minimum(ia_safe, ib_safe), ia_safe))
        out = [jnp.where(better, vb, va), jnp.where(idx < _BIG, idx, -1)]
    elif agg.combine in (("first",), ("last",)):
        va, pa = acc_inters
        vb, pb = inters
        if agg.combine == ("last",):
            take_b = (pb >= 0) & ((pa < 0) | (pb > pa))
        else:
            take_b = (pb < _BIG) & ((pa >= _BIG) | (pb < pa))
        out = [jnp.where(take_b, vb, va), jnp.where(take_b, pb, pa)]
    else:
        for a, b, op in zip(acc_inters, inters, agg.combine):
            out.append(_pair_merge(op, a, b, nat=nat_markers))
    return out, acc_counts + counts


def _init_state_like_merged(agg: Aggregation, inters, counts, *, nat: bool):
    """Cast the first slab's state to the dtypes a merge would produce.

    Custom callable combines may promote (``jnp.stack([a, b]).sum(0)``
    widens int32 chunk counts to int64 under x64), so without this the
    carry pytree changes dtype between slab 1 and slab 2 — a step retrace,
    and a donated init buffer that cannot alias its output. The self-merge
    here is traced only for its (static) output dtypes; XLA DCEs the
    computation, so the init step costs nothing extra."""
    import jax

    merged = _merge_into(agg, (inters, counts), inters, counts, nat=nat)
    return jax.tree.map(lambda x, m: x.astype(m.dtype), (inters, counts), merged)


def _build_step(agg: Aggregation, *, size: int, count_skipna: bool,
                nat: bool = False):
    """One jitted step: slab -> chunk intermediates -> merge into state.
    The carry is donated (pipeline.maybe_donate) so the dense accumulators
    update in place across slabs; the first call's ``state=None`` donates
    an empty pytree, so one jitted function covers both arities."""
    from .pipeline import maybe_donate

    def step(state, slab, ccodes, offset):
        inters, counts = _slab_stats(
            agg, slab, ccodes, offset, size=size, count_skipna=count_skipna, nat=nat
        )
        if state is None:
            return _init_state_like_merged(agg, inters, counts, nat=nat)
        return _merge_into(agg, state, inters, counts, nat=nat)

    jitted = maybe_donate(step, donate_argnums=(0,))

    def run(state, slab, ccodes, offset):
        # first call establishes the state pytree; jit caches both arities
        return jitted(state, slab, ccodes, offset)

    # the OOM-split tests assert compile counts against the underlying jit
    # cache (the power-of-two ladder claim: splits reuse rungs, the base
    # step is never retraced)
    run._jitted = jitted
    return run


def _build_mesh_step(agg: Aggregation, *, size: int,
                     count_skipna: bool, nat: bool, mesh, axes, lead_ndim: int):
    """Per-slab step on the mesh: each device folds its shard of the slab
    into ITS OWN accumulator — zero collectives while streaming. State
    leaves are (ndev, ..., size) sharded over the leading device axis;
    the one collective combine happens in :func:`_build_mesh_final`.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from .parallel.mapreduce import _flat_axis_index

    spec_entry = axes if len(axes) > 1 else axes[0]
    slab_spec = P(*([None] * lead_ndim + [spec_entry]))

    def local_step(state, slab_sh, codes_sh, offset):
        # shard-contiguous layout: device d holds slab[d*L:(d+1)*L], so the
        # global position of its first element is offset + d*L. L comes
        # from the traced shard's own trailing dim, NOT the batch_len this
        # builder was keyed on: an OOM-split sub-slab re-enters the same
        # jitted step at half the span, and a static L would misplace every
        # position-tracking reduction (argmin/argmax/first/last)
        dev = _flat_axis_index(axes)
        goff = offset + dev.astype(offset.dtype) * slab_sh.shape[-1]
        inters, counts = _slab_stats(
            agg, slab_sh, codes_sh, goff, size=size,
            count_skipna=count_skipna, nat=nat,
        )
        if state is None:
            inters, counts = _init_state_like_merged(agg, inters, counts, nat=nat)
            return _expand_dev(inters), counts[None]
        st = jax.tree.map(lambda x: x[0], state)
        minters, mcounts = _merge_into(agg, st, inters, counts, nat=nat)
        return _expand_dev(minters), mcounts[None]

    return _mesh_step_runner(local_step, mesh, slab_spec, spec_entry)


def _mesh_step_runner(local_step, mesh, slab_spec, spec_entry):
    """Two jitted shard_map programs (first slab has no state yet). The
    steady-state program donates the per-device carry so every chip's
    accumulators update in place across slabs."""
    import jax
    from jax.sharding import PartitionSpec as P

    from .parallel.mesh import shard_map
    from .pipeline import maybe_donate

    def init_step(slab_sh, codes_sh, offset):
        return local_step(None, slab_sh, codes_sh, offset)

    common = dict(mesh=mesh, out_specs=P(spec_entry), check_vma=False)
    init_fn = jax.jit(shard_map(
        init_step, in_specs=(slab_spec, P(spec_entry), P()), **common
    ))
    step_fn = maybe_donate(shard_map(
        local_step, in_specs=(P(spec_entry), slab_spec, P(spec_entry), P()), **common
    ), donate_argnums=(0,))

    def run(state, slab, ccodes, offset):
        if state is None:
            return init_fn(slab, ccodes, offset)
        return step_fn(state, slab, ccodes, offset)

    # the costmodel card site lowers the underlying jitted programs (the
    # steady-state carry step and the first-slab init) without executing
    run._jitted = step_fn
    run._jitted_init = init_fn
    return run


def _expand_dev(inters):
    """Re-attach the per-device leading axis to every accumulator leaf."""
    import jax

    return jax.tree.map(lambda x: x[None], inters)


def _build_mesh_final(agg: Aggregation, *, mesh, axes, nat: bool):
    """The ONE collective combine: per-device accumulated states meet the
    SAME combine contract as the mesh map-reduce program — literally the
    shared ``_combine_intermediates``/``_finalize_combined`` helpers in
    parallel/mapreduce.py. Output replicated."""
    import jax
    from jax.sharding import PartitionSpec as P

    from .parallel.mapreduce import _combine_intermediates, _finalize_combined
    from .parallel.mesh import shard_map

    spec_entry = axes if len(axes) > 1 else axes[0]

    def final(state):
        st = jax.tree.map(lambda x: x[0], state)
        inters, counts = st
        counts_g = jax.lax.psum(counts, axes)
        combined = _combine_intermediates(agg, inters, axes, nat)
        return _finalize_combined(agg, combined, counts_g)

    return jax.jit(
        shard_map(
            final, mesh=mesh, in_specs=(P(spec_entry),), out_specs=P(),
            check_vma=False,
        )
    )


def _build_mesh_step_blocked(agg: Aggregation, *, size_pad: int, ndev: int,
                             count_skipna: bool, nat: bool, mesh, axes,
                             lead_ndim: int):
    """Huge-label-space streaming (the streaming form of the blocked
    owner-by-owner program, parallel/mapreduce.py): per slab, a fori_loop
    walks the ndev owner blocks — each block's (..., size/ndev)
    intermediates are psum'd and the owner keeps its slice — so no dense
    (..., size) buffer ever materializes on any device, per slab OR in the
    accumulators. Communication per slab totals one psum of (..., size);
    the data makes ndev passes per slab (the price of the ceiling).
    Additive combines only (sum / the var triple)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .parallel.mapreduce import (
        _combine_simple,
        _combine_var,
        _flat_axis_index,
        _local_chunk,
        _local_counts,
    )

    spec_entry = axes if len(axes) > 1 else axes[0]
    slab_spec = P(*([None] * lead_ndim + [spec_entry]))
    b = size_pad // ndev
    skipna = agg.name.startswith("nan")
    nat_markers = nat and not skipna

    def local_step(state, slab_sh, codes_sh, offset):
        me = _flat_axis_index(axes)

        def block(d):
            in_block = (codes_sh >= d * b) & (codes_sh < (d + 1) * b)
            bc = jnp.where(in_block, codes_sh - d * b, -1)
            counts = jax.lax.psum(
                _local_counts(bc, slab_sh, b, count_skipna, nat), axes
            )
            outs = []
            for inter, op in zip(_local_chunk(agg, bc, slab_sh, b, nat), agg.combine):
                outs.append(
                    _combine_var(inter, axes)
                    if op == "var"
                    else _combine_simple(op, inter, axes, nat=nat_markers)
                )
            return counts, outs

        c0, o0 = block(0)
        keep0 = me == 0
        carry0 = jax.tree.map(lambda x: jnp.where(keep0, x, jnp.zeros_like(x)), (c0, o0))

        def body(d, carry):
            c, o = block(d)
            keep = me == d
            return jax.tree.map(lambda new, acc: jnp.where(keep, new, acc), (c, o), carry)

        counts_blk, inters_blk = jax.lax.fori_loop(1, ndev, body, carry0)
        if state is None:
            inters_blk, counts_blk = _init_state_like_merged(
                agg, inters_blk, counts_blk, nat=nat
            )
            return _expand_dev(inters_blk), counts_blk[None]
        st = jax.tree.map(lambda x: x[0], state)
        acc_inters, acc_counts = st
        merged = [
            _pair_merge(op, a, new, nat=nat_markers)
            for a, new, op in zip(acc_inters, inters_blk, agg.combine)
        ]
        return _expand_dev(merged), (acc_counts + counts_blk)[None]

    return _mesh_step_runner(local_step, mesh, slab_spec, spec_entry)


def _build_mesh_final_blocked(agg: Aggregation, *, size: int, mesh, axes):
    """Finalize per-owner accumulators and gather the full group axis —
    the tail of the blocked owner-by-owner program."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .parallel.mapreduce import _crop, _finalize_combined
    from .parallel.mesh import shard_map

    spec_entry = axes if len(axes) > 1 else axes[0]

    def final(state):
        st = jax.tree.map(lambda x: x[0], state)
        inters, counts = st
        result_own = _finalize_combined(agg, inters, counts)
        full = jax.lax.all_gather(
            jnp.moveaxis(result_own, -1, 0), axes, tiled=True
        )
        return _crop(jnp.moveaxis(full, 0, -1), size)

    return jax.jit(
        shard_map(
            final, mesh=mesh, in_specs=(P(spec_entry),), out_specs=P(),
            check_vma=False,
        )
    )


def streaming_groupby_scan(
    array: Any,
    by: Any,
    *,
    func: str,
    batch_len: int | None = None,
    batch_bytes: int | None = None,
    expected_groups: Any = None,
    dtype: Any = None,
    out: Callable[[int, int, Any], None] | None = None,
    mesh: Any = None,
    axis_name: str | tuple[str, ...] = "data",
) -> Any:
    """Out-of-core grouped scan: slabs stream through a per-group carry.

    The reference runs scans over chunked arrays via dask's cumreduction
    (dask.py:576-663); this is the sequential form of the same Blelloch
    decomposition — each slab runs the within-slab segmented scan, the
    per-group block summary becomes the next slab's carry, and the carry
    is applied through the codes. ``bfill`` streams the slabs in REVERSE
    (the ``(start, stop)`` loader contract is random-access).

    ``array``: host array ``(..., n)`` or loader ``callable(start, stop)``;
    ``by``: 1-D labels along the streamed (scan) axis. ``out``: optional
    writer ``callable(start, stop, result_slab)`` — with a writer the
    result streams straight back out (nothing array-sized materializes;
    returns None); without one the full result array is allocated.
    Semantics match :func:`flox_tpu.groupby_scan` exactly, including
    datetime64/timedelta64 NaT rules and int promotion.

    ``mesh=`` completes the composition matrix: each slab scatters over
    the mesh and runs the SAME distributed Blelloch program as the
    in-memory mesh scan (within-slab carry exchange over the collective),
    with the cross-slab carry folded at the slab boundary — out-of-core
    AND multi-chip scans, results still streamable through ``out=``.
    """
    from . import telemetry

    with telemetry.span("streaming_groupby_scan", func=func, mesh=mesh is not None):
        return _streaming_groupby_scan_impl(
            array, by, func=func, batch_len=batch_len, batch_bytes=batch_bytes,
            expected_groups=expected_groups, dtype=dtype, out=out,
            mesh=mesh, axis_name=axis_name,
        )


def _streaming_groupby_scan_impl(
    array: Any,
    by: Any,
    *,
    func: str,
    batch_len: int | None,
    batch_bytes: int | None,
    expected_groups: Any,
    dtype: Any,
    out: Callable[[int, int, Any], None] | None,
    mesh: Any,
    axis_name: str | tuple[str, ...],
) -> Any:
    """The :func:`streaming_groupby_scan` body, under the public wrapper's
    root telemetry span (defaults live only on the wrapper)."""
    import math

    import jax
    import jax.numpy as jnp

    from . import dtypes as dtps, telemetry
    from .aggregations import _initialize_scan
    from .core import _convert_expected_groups_to_index, _normalize_expected, _normalize_isbin
    from .kernels import _nan_mask, generic_kernel
    from .profiling import timed

    labels = utils.asarray_host(by)
    if labels.ndim != 1:
        raise NotImplementedError(
            "streaming_groupby_scan scans the streamed axis: pass 1-D labels "
            "(use groupby_scan for in-memory nD layouts)"
        )
    n = labels.shape[0]

    if callable(array):
        loader = array
        lead_shape = None
    else:
        arr = np.asarray(array) if not utils.is_jax_array(array) else array
        if arr.shape[-1] != n:
            raise ValueError(
                f"array trailing dim {arr.shape[-1]} != by length {n}"
            )
        lead_shape = arr.shape[:-1]
        loader = lambda s, e: arr[..., s:e]

    expected = _normalize_expected(expected_groups, 1)
    expected_idx = _convert_expected_groups_to_index(expected, _normalize_isbin(False, 1), True)
    with telemetry.span("factorize") as _fsp:
        codes, found_groups, grp_shape, ngroups, size, props = fct.factorize_(
            [labels], axes=(0,), expected_groups=expected_idx, sort=True
        )
        _fsp.set(ngroups=ngroups, size=size)
    # ONE contiguous int32 copy for the whole stream (per-slab slices are
    # zero-copy contiguous views; see streaming_groupby_reduce)
    codes = np.ascontiguousarray(np.asarray(codes).reshape(-1), dtype=np.int32)
    if size == 0:
        raise ValueError("No groups to scan over (empty expected_groups?)")

    scan = _initialize_scan(func)

    probe = np.asarray(loader(0, 1))
    if lead_shape is None:
        lead_shape = probe.shape[:-1]
    arr_dtype = probe.dtype
    datetime_dtype = arr_dtype if dtps.is_datetime_like(arr_dtype) else None
    nat = datetime_dtype is not None
    base_loader = loader
    if nat:
        # same rules as groupby_scan (scan.py:118-151)
        if scan.name in ("cumsum", "nancumsum") and arr_dtype.kind == "M":
            raise TypeError(
                "cumsum of datetime64 values is undefined (numpy cannot add "
                "points in time); cumsum timedelta64 works."
            )
        if dtype is not None:
            raise TypeError(
                "dtype= is not supported for datetime/timedelta scans; the "
                "scan runs on the exact int64 view and returns "
                f"{arr_dtype} unchanged."
            )
        if not utils.x64_enabled():
            raise ValueError(
                "datetime/timedelta streaming scans need jax_enable_x64 "
                "(int64 NaT sentinels do not survive int32 truncation)."
            )
        loader = lambda s, e: np.asarray(base_loader(s, e)).view("int64")
    # int promotion for accumulating scans (parity: scan.py:153-156)
    if scan.name in ("cumsum", "nancumsum") and dtype is None and not nat:
        if arr_dtype.kind in "iub":
            dtype = np.result_type(arr_dtype, np.int_)

    itemsize = probe.dtype.itemsize
    row_bytes = int(np.prod(lead_shape, dtype=np.int64)) * itemsize if lead_shape else itemsize
    if batch_len is None:
        if batch_bytes is None:
            batch_bytes = _DEFAULT_BATCH_BYTES
        batch_len = max(1, min(n, batch_bytes // max(row_bytes, 1)))
    nbatches = math.ceil(n / batch_len)

    has_missing = bool((codes < 0).any())
    reverse = scan.name == "bfill"
    kw = {"nat": True} if nat else {}

    def apply_carry_codes(table, ccodes):
        safe = jnp.where(ccodes < 0, size, ccodes)
        pad = jnp.zeros(table.shape[:-1] + (1,), table.dtype)
        return jnp.take(jnp.concatenate([table, pad], axis=-1), safe, axis=-1)

    if scan.mode == "apply_binary_op":

        def slab_scan(slab, ccodes, carry, had):
            local = generic_kernel(scan.scan, ccodes, slab, size=size, dtype=dtype, **kw)
            if nat:
                from .kernels import _NAT_INT

                is_nat = slab == jnp.asarray(_NAT_INT, slab.dtype)
                summed = jnp.where(is_nat, jnp.zeros((), slab.dtype), slab)
            else:
                summed = slab
            block = generic_kernel(
                scan.reduction, ccodes, summed, size=size, fill_value=0
            ).astype(local.dtype)
            if carry is None:
                out_slab = local
                new_carry = block
            else:
                out_slab = local + apply_carry_codes(carry, ccodes)
                new_carry = carry + block
            new_had = had
            if nat and scan.scan == "cumsum":
                # non-skipna datetime poisoning: a NaT earlier in the group
                # poisons everything after — sticky per-group channel
                from .kernels import _NAT_INT

                had_slab = generic_kernel(
                    "sum", ccodes, is_nat.astype(jnp.int32), size=size, fill_value=0
                ) > 0
                nat_val = jnp.asarray(_NAT_INT, out_slab.dtype)
                if had is not None:
                    poison_e = apply_carry_codes(had.astype(jnp.int8), ccodes) > 0
                    out_slab = jnp.where(poison_e, nat_val, out_slab)
                    new_had = had | had_slab
                else:
                    new_had = had_slab
                out_slab = jnp.where(local == nat_val, nat_val, out_slab)
            return out_slab, new_carry, new_had

    else:  # ffill / bfill

        def slab_scan(slab, ccodes, carry, has):
            local = generic_kernel(scan.scan, ccodes, slab, size=size, **kw)
            is_float = jnp.issubdtype(slab.dtype, jnp.floating)
            valid_cnt = generic_kernel("nanlen", ccodes, slab, size=size, **kw)
            edge_val = generic_kernel(
                scan.reduction, ccodes, slab, size=size,
                fill_value=jnp.nan if is_float else 0, **kw,
            )
            mask = _nan_mask(local, nat)
            still = ~mask if mask is not None else jnp.zeros(local.shape, bool)
            out_slab = local
            if carry is not None:
                carry_e = apply_carry_codes(carry, ccodes)
                has_e = apply_carry_codes(has.astype(jnp.int8), ccodes) > 0
                out_slab = jnp.where(still & has_e & (ccodes >= 0), carry_e, local)
                new_carry = jnp.where(valid_cnt > 0, edge_val.astype(carry.dtype), carry)
                new_has = has | (valid_cnt > 0)
            else:
                new_carry = edge_val
                new_has = valid_cnt > 0
            return out_slab, new_carry, new_has

    if mesh is not None:
        return _run_mesh_stream_scan(
            scan, loader, codes, size=size, n=n, batch_len=batch_len,
            lead_shape=tuple(lead_shape), dtype=dtype, nat=nat,
            datetime_dtype=datetime_dtype, has_missing=has_missing,
            reverse=reverse, out=out, mesh=mesh, axis_name=axis_name,
            # the wrap views datetimes as int64; no second loader probe
            probe_dtype=np.dtype("int64") if nat else probe.dtype,
            data_probe=probe,
        )

    from .pipeline import SlabStager, maybe_donate, stream_slabs
    from .resilience import (
        StreamCheckpointer,
        StreamCounters,
        device_restore,
        dispatch_slab,
    )

    init_fn, step_fn = _step_cached(
        ("scan-step", scan.name, size, nat, str(dtype), has_missing),
        lambda: (
            jax.jit(lambda slab, ccodes: slab_scan(slab, ccodes, None, None)),
            # the per-group carry (and the sticky NaT/has channel) is
            # donated: updated in place across slabs
            maybe_donate(slab_scan, donate_argnums=(2, 3)),
        ),
    )

    counters = StreamCounters()
    stager = SlabStager(
        loader, codes, n=n, batch_len=batch_len, lead_shape=tuple(lead_shape),
        pad=False, counters=counters,
    )
    # checkpointing a scan needs the already-emitted slabs to survive the
    # kill, which only a writer gives us (the in-memory result array dies
    # with the run) — so snapshots are taken only on the out= path
    ckpt = StreamCheckpointer.for_stream(
        kind="scan", name=_scan_ckpt_id(scan), n=n, batch_len=batch_len, size=size,
        codes=codes, lead_shape=tuple(lead_shape),
        extra=(nat, str(dtype), has_missing, reverse), data_probe=probe,
        counters=counters, enabled=out is not None,
    )
    carry = had = None
    skip = 0
    snap = ckpt.restore()
    if snap is not None:
        skip = snap.slabs_done
        carry, had = device_restore(snap.payload)
    done = skip

    result_arr = None

    def apply_scan(cur, sb):
        c, h = cur
        if c is None:
            out_slab, c, h = init_fn(sb.data, sb.codes)
        else:
            out_slab, c, h = step_fn(sb.data, sb.codes, c, h)
        nonlocal result_arr
        result_arr = _emit_scan_slab(
            out_slab, sb.codes_host, sb.start, sb.stop, nat=nat,
            datetime_dtype=datetime_dtype, has_missing=has_missing, out=out,
            result_arr=result_arr, lead_shape=lead_shape, n=n,
        )
        return c, h

    with timed(f"stream-scan [{scan.name}] {nbatches} slab(s)"):
        # prefetch overlaps the next load with this slab's compute+emit
        # (the emit's host conversion syncs per slab, so no dispatch
        # throttle is needed here); pad=False keeps the single-device scan
        # contract of ragged tail slabs. An OOM-split sub-slab stays ragged
        # too, and splits run in reverse span order for bfill so the carry
        # still flows against the stream.
        for sl in stream_slabs(
            loader, codes, n=n, batch_len=batch_len, lead_shape=tuple(lead_shape),
            pad=False, reverse=reverse, label=f"scan[{scan.name}]",
            skip=skip, counters=counters, stager=stager,
        ):
            carry, had = dispatch_slab(
                apply_scan, (carry, had), sl, stager=stager, counters=counters,
                reverse=reverse,
            )
            done += 1
            ckpt.tick(lambda: (carry, had), slabs_done=done)
    ckpt.done()
    if out is not None:
        return None
    return result_arr


def _scan_ckpt_id(scan) -> str:
    """Resolved Scan identity for the checkpoint key (the scan-side
    analogue of the reduce path's ``repr(_agg_cache_key(agg))``): a custom
    Scan sharing a builtin's name must MISS the builtin's snapshot, never
    silently fold into it. Callable binary_ops carry id(), so cross-process
    resume of a custom scan misses too — a fresh run, never a mismatch."""
    op = scan.binary_op
    op_id = None if op is None else (getattr(op, "__qualname__", repr(op)), id(op))
    return repr((
        scan.name, scan.scan, scan.reduction, op_id, scan.identity,
        scan.mode, scan.preserves_dtype,
    ))


def _emit_scan_slab(out_slab, ccodes_np, s, e, *, nat, datetime_dtype,
                    has_missing, out, result_arr, lead_shape, n):
    """Trim/mask/view one scanned slab and hand it to the writer or the
    result array — the ONE emit step both scan loops (single-device and
    mesh) share, so missing-label masking and the datetime view cannot
    drift between them. Returns the (possibly just-allocated) result
    array."""
    res = np.asarray(out_slab)[..., : e - s]
    if has_missing:
        from .scan import _mask_positions

        res = np.asarray(_mask_positions(res, ccodes_np[: e - s] < 0, nat=nat))
    if nat:
        res = res.astype("int64").view(datetime_dtype)
    if out is not None:
        out(s, e, res)
        return result_arr
    if result_arr is None:
        result_arr = np.empty(tuple(lead_shape) + (n,), res.dtype)
    result_arr[..., s:e] = res
    return result_arr


def _run_mesh_stream_scan(scan, loader, codes, *, size, n, batch_len, lead_shape,
                          dtype, nat, datetime_dtype, has_missing, reverse,
                          out, mesh, axis_name, probe_dtype, data_probe=None):
    """streaming × mesh scan: each slab runs the distributed Blelloch with
    cross-slab carry I/O (parallel.scan.build_stream_scan_step)."""
    import math

    import jax.numpy as jnp

    from .profiling import timed

    axes, _ndev, batch_len, _spec_entry, _sspec, _cspec, slab_shard, codes_shard = (
        _mesh_stream_layout(mesh, axis_name, batch_len, len(lead_shape))
    )
    nbatches = math.ceil(n / batch_len)

    from .parallel.scan import build_stream_scan_step

    step = _step_cached(
        ("scan-mesh-step", scan.name, size, nat, str(dtype), axes, mesh,
         len(lead_shape)),
        lambda: build_stream_scan_step(
            scan, size=size, mesh=mesh, axis_name=axes, nat=nat,
            lead_ndim=len(lead_shape),
        ),
    )

    # carry init needs the working dtype up front: the promoted/cast slab
    # dtype for cumsum sums and ffill edge values
    work_dtype = np.dtype(dtype) if dtype is not None else probe_dtype
    c0 = jnp.zeros(lead_shape + (size,), work_dtype)
    c1 = jnp.zeros(lead_shape + (size,), jnp.int8)  # had-NaT / has-value

    if dtype is not None:
        # fold the promotion cast into the (possibly prefetched) staging
        base_loader = loader
        loader = lambda s, e: np.asarray(base_loader(s, e)).astype(work_dtype, copy=False)

    from .pipeline import SlabStager, stream_slabs
    from .resilience import (
        StreamCheckpointer,
        StreamCounters,
        device_restore,
        dispatch_slab,
    )

    counters = StreamCounters()
    stager = SlabStager(
        loader, codes, n=n, batch_len=batch_len, lead_shape=tuple(lead_shape),
        slab_shard=slab_shard, codes_shard=codes_shard, counters=counters,
    )
    # writer-gated for the same reason as the single-device scan; the carry
    # pair is replicated (out_specs P()), so restore needs no resharding
    ckpt = StreamCheckpointer.for_stream(
        kind="scan-mesh", name=_scan_ckpt_id(scan), n=n, batch_len=batch_len, size=size,
        codes=codes, lead_shape=tuple(lead_shape),
        extra=(nat, str(dtype), has_missing, reverse, tuple(axes)),
        data_probe=data_probe, counters=counters, enabled=out is not None,
    )
    skip = 0
    snap = ckpt.restore()
    if snap is not None:
        skip = snap.slabs_done
        c0, c1 = device_restore(snap.payload)
    done = skip

    result_arr = None

    def apply_scan(cur, sb):
        a, b = cur
        out_sh, a, b = step(sb.data, sb.codes, a, b)
        nonlocal result_arr
        result_arr = _emit_scan_slab(
            out_sh, sb.codes_host, sb.start, sb.stop, nat=nat,
            datetime_dtype=datetime_dtype, has_missing=has_missing, out=out,
            result_arr=result_arr, lead_shape=lead_shape, n=n,
        )
        return a, b

    with timed(f"stream-scan-mesh [{scan.name}] {nbatches} slab(s)"):
        # the emit's host conversion syncs per slab (no throttle needed);
        # prefetch overlaps the next slab's load+scatter with it. No OOM
        # splitting here (stager=None): the distributed Blelloch carry
        # exchange is not sub-slab associative under padding, so an OOM
        # surfaces rather than risking a wrong fold — retry, checkpoint,
        # and the fault hook still apply.
        for sl in stream_slabs(
            loader, codes, n=n, batch_len=batch_len, lead_shape=tuple(lead_shape),
            slab_shard=slab_shard, codes_shard=codes_shard, reverse=reverse,
            label=f"scan-mesh[{scan.name}]", skip=skip, counters=counters,
            stager=stager,
        ):
            c0, c1 = dispatch_slab(
                apply_scan, (c0, c1), sl, counters=counters, reverse=reverse,
            )
            done += 1
            ckpt.tick(lambda: (c0, c1), slabs_done=done)
    ckpt.done()
    if out is not None:
        return None
    return result_arr


def _stream_quantile(agg: Aggregation, loader, codes, *, size: int, n: int,
                     batch_len: int, lead_shape: tuple, probe_dtype,
                     mesh=None, axis_name="data", data_probe=None):
    """Out-of-core EXACT quantile/median: the radix-select bisection
    (kernels._radix_select) only ever consumes per-group COUNTS, and counts
    accumulate slab by slab — so order statistics stream in ``nbits + 1``
    full passes over the loader (1 count pass + one per key bit; 33 for
    f32, 65 for f64). The reference cannot do this at all: its chunked
    quantile requires whole groups per block (dask.py's blockwise
    constraint). Bit-identical to the eager select path — same counts,
    same bit-by-bit reconstruction.

    IO cost is the point to understand: the data is read ``nbits + 1``
    times. For a zarr/S3 loader that is ``nbits + 1`` remote sweeps — an
    explicit, documented trade for never materializing the array.
    """
    import math

    import jax
    import jax.numpy as jnp

    from .kernels import (
        _from_leading,
        _nan_mask,
        _quantile_alpha_beta,
        _quantile_rank_sets,
        _radix_pass_count,
        _radix_update,
        _safe_codes,
        _seg,
        _to_leading,
        _uint_type,
        _uint_to_value,
        _valid_keys,
        _counts,
    )
    from .profiling import timed

    skipna = agg.name.startswith("nan")
    fkw = dict(agg.finalize_kwargs)
    if agg.name in ("median", "nanmedian"):
        q, method = 0.5, "linear"
    else:
        if "q" not in fkw:
            raise TypeError(f"{agg.name} requires finalize_kwargs={{'q': ...}}")
        q = fkw["q"]
        method = fkw.get("method", "linear")
    qs = np.atleast_1d(np.asarray(q, dtype=np.float64))
    scalar_q = np.ndim(q) == 0
    alpha, beta = _quantile_alpha_beta(method)

    axes = None
    slab_shard = codes_shard = None
    shard_quantum = 1
    if mesh is not None:
        # out-of-core AND distributed: slabs scatter over the mesh and each
        # counting pass psums — the per-group bisection state is replicated,
        # so the two compositions stack with no new machinery. The layout
        # comes from the SAME helper the reduce runtime uses.
        axes, _ndev, batch_len, _spec_entry, sspec, cspec, slab_shard, codes_shard = (
            _mesh_stream_layout(mesh, axis_name, batch_len, len(lead_shape))
        )
        shard_quantum = _ndev
    nbatches = math.ceil(n / batch_len)

    from .pipeline import DispatchThrottle, SlabStager, stream_slabs
    from .resilience import (
        StreamCheckpointer,
        StreamCounters,
        device_restore,
        dispatch_slab,
    )

    counters = StreamCounters()
    # ONE stager for all nbits + 1 passes: the retry policy and the loader
    # dtype contract hold across the whole multi-pass run
    stager = SlabStager(
        loader, codes, n=n, batch_len=batch_len, lead_shape=tuple(lead_shape),
        slab_shard=slab_shard, codes_shard=codes_shard, counters=counters,
    )

    def slabs(pass_label, skip=0):
        # each counting pass is one full pipelined sweep over the loader:
        # prefetch restarts per pass (the loader contract is random-access)
        return stream_slabs(
            loader, codes, n=n, batch_len=batch_len, lead_shape=tuple(lead_shape),
            slab_shard=slab_shard, codes_shard=codes_shard,
            label=f"quantile[{agg.name}] {pass_label}",
            skip=skip, counters=counters, stager=stager,
        )

    # resolved float dtype: same rule as the eager kernel (probe_dtype comes
    # from the caller's one probe — no second remote chunk read). MUST be
    # the CANONICALIZED dtype: with x64 off jax downcasts f64 slabs to f32,
    # and keying nbits off the host dtype would run 65 passes on uint32
    # keys — out-of-range shifts (implementation-defined on TPU) and double
    # the loader IO
    from jax.dtypes import canonicalize_dtype

    if np.issubdtype(probe_dtype, np.floating):
        fdtype = canonicalize_dtype(probe_dtype)
    else:
        fdtype = jnp.float64 if utils.x64_enabled() else jnp.float32
    ut = _uint_type(fdtype)
    nbits = jnp.dtype(ut).itemsize * 8
    cdtype = jnp.float32 if n < 2**24 else jnp.int32

    def prep(slab):
        data = _to_leading(slab)
        if data.dtype != fdtype:
            data = data.astype(fdtype)
        return data

    def _build_passes():
        def count_pass(nn, hasnan, slab, ccodes):
            data = prep(slab)
            sc = _safe_codes(ccodes, size)
            mask = _nan_mask(data)
            nn_add = _counts(sc, size, mask=mask)
            hn = _seg("max", (~mask).astype(jnp.int8), sc, size) if (
                not skipna and mask is not None
            ) else None
            if axes is not None:
                nn_add = jax.lax.psum(nn_add, axes)
                if hn is not None:
                    hn = jax.lax.pmax(hn, axes)
            nn = nn + nn_add
            if hn is not None:
                hasnan = jnp.maximum(hasnan, hn)
            return nn, hasnan

        def bit_pass(cnt, prefix, slab, ccodes, bshift):
            data = prep(slab)
            keys = _valid_keys(data, _nan_mask(data))
            add = _radix_pass_count(
                keys, _safe_codes(ccodes, size), size, prefix, bshift, cdtype
            )
            if axes is not None:
                add = jax.lax.psum(add, axes)
            return cnt + add

        # pass accumulators are donated (pipeline.maybe_donate): nn/hasnan
        # and the per-bit cnt update in place across slabs, and the
        # bisection state updates in place across bits. prefix/rank are NOT
        # donated into bit_pass — prefix is re-read for every slab of a pass
        from .pipeline import maybe_donate

        if axes is None:
            return (
                maybe_donate(count_pass, donate_argnums=(0, 1)),
                maybe_donate(bit_pass, donate_argnums=(0,)),
                maybe_donate(_radix_update, donate_argnums=(0, 1)),
            )

        # mesh: slab/codes sharded in (the SAME sspec/cspec the staging
        # pipeline uses); bisection state replicated in AND out
        from jax.sharding import PartitionSpec as P

        from .parallel.mesh import shard_map

        return (
            maybe_donate(shard_map(
                count_pass, mesh=mesh,
                in_specs=(P(), P(), sspec, cspec), out_specs=P(),
                check_vma=False,
            ), donate_argnums=(0, 1)),
            maybe_donate(shard_map(
                bit_pass, mesh=mesh,
                in_specs=(P(), P(), sspec, cspec, P()), out_specs=P(),
                check_vma=False,
            ), donate_argnums=(0,)),
            maybe_donate(_radix_update, donate_argnums=(0, 1)),
        )

    count_pass, bit_pass, update = _step_cached(
        ("quantile-pass", size, str(fdtype), str(cdtype), skipna,
         None if axes is None else (axes, mesh), len(lead_shape)),
        _build_passes,
    )

    # multi-pass checkpointing: phase 0 = the count pass (payload nn/hasnan),
    # phase 1+i = bit pass i (payload carries the full bisection state —
    # nn/hasnan for the finalize, prefix/rank for the bisection, cnt for the
    # pass in flight). The rank-set meta is NOT checkpointed: it re-derives
    # deterministically from the restored nn.
    ckpt = StreamCheckpointer.for_stream(
        kind="quantile", name=agg.name, n=n, batch_len=batch_len, size=size,
        codes=codes, lead_shape=tuple(lead_shape),
        mesh_key=None if axes is None else tuple(axes),
        extra=(tuple(np.asarray(qs).tolist()), method, str(fdtype)),
        data_probe=data_probe, counters=counters,
    )
    snap = ckpt.restore()
    phase0, skip0 = (0, 0) if snap is None else (snap.phase, snap.slabs_done)

    trail = lead_shape  # leading layout puts the reduce axis first
    throttle = DispatchThrottle()

    def apply_count(st, sb):
        return count_pass(st[0], st[1], sb.data, sb.codes)

    with timed(f"stream-quantile [{agg.name}] {nbits + 1} passes x {nbatches} slab(s)"):
        # counts accumulate EXACTLY in int32 (f32 would drift past 2^24 and
        # shift rank positions — the bit-identity claim rests on this)
        bit0, bit_skip, cnt0 = 0, 0, None
        if phase0 == 0:
            if snap is not None:
                nn, hasnan = device_restore(snap.payload)
            else:
                nn = jnp.zeros((size,) + trail, jnp.int32)
                hasnan = jnp.zeros((size,) + trail, jnp.int8)
            done = skip0
            for sl in slabs("count", skip=skip0):
                nn, hasnan = dispatch_slab(
                    apply_count, (nn, hasnan), sl, stager=stager,
                    counters=counters, shard_quantum=shard_quantum,
                    highcard_hint=_highcard_oom_hint(agg, size, None),
                )
                throttle.tick(nn)
                done += 1
                ckpt.tick(lambda: (nn, hasnan), slabs_done=done, phase=0)
        else:
            nn, hasnan, prefix, rank, cnt0 = device_restore(snap.payload)
            bit0, bit_skip = phase0 - 1, skip0

        idx_dtype = jnp.float64 if utils.x64_enabled() else jnp.float32
        nnf = nn.astype(idx_dtype)
        ranks, meta = _quantile_rank_sets(qs, nnf, method, alpha, beta)
        m = ranks.shape[0]
        if phase0 == 0:
            prefix = jnp.zeros((m, size) + trail, ut)
            rank = ranks.astype(jnp.int32)
        for i in range(bit0, nbits):
            bshift = jnp.asarray(nbits - 1 - i, ut)
            if i == bit0 and cnt0 is not None:
                cnt, skip_i = cnt0, bit_skip
            else:
                cnt, skip_i = jnp.zeros((m, size) + trail, jnp.int32), 0

            def apply_bit(st, sb):
                return bit_pass(st, prefix, sb.data, sb.codes, bshift)

            done = skip_i
            for sl in slabs(f"bit {i}", skip=skip_i):
                cnt = dispatch_slab(
                    apply_bit, cnt, sl, stager=stager, counters=counters,
                    shard_quantum=shard_quantum,
                    highcard_hint=_highcard_oom_hint(agg, size, None),
                )
                throttle.tick(cnt)
                done += 1
                ckpt.tick(
                    lambda: (nn, hasnan, prefix, rank, cnt),
                    slabs_done=done, phase=1 + i,
                )
            prefix, rank = update(prefix, rank, cnt, bshift)
    ckpt.done()

    selected = _uint_to_value(prefix, fdtype)
    group_has_nan = (hasnan > 0) if not skipna else None
    fv = agg.final_fill_value
    try:
        fv_arr = jnp.asarray(np.nan if fv is None else fv, fdtype)
    except (TypeError, ValueError):
        fv_arr = jnp.asarray(jnp.nan, fdtype)
    threshold = max(agg.min_count, 1)

    from .kernels import _quantile_interp_value

    outs = []
    for k, _qi in enumerate(qs):
        val = _quantile_interp_value(method, meta[k], selected, fdtype)
        val = jnp.where(nn < threshold, fv_arr, val)
        if group_has_nan is not None:
            val = jnp.where(group_has_nan, jnp.asarray(jnp.nan, fdtype), val)
        outs.append(_from_leading(val))
    if scalar_q:
        return outs[0]
    return jnp.stack(outs, axis=0)


def _argmerge_better(va, vb, arg_of_max: bool):
    import jax.numpy as jnp

    better = (vb > va) if arg_of_max else (vb < va)
    if jnp.issubdtype(va.dtype, jnp.floating):
        # NaN-propagating semantics: a NaN extreme wins over a number
        better = better | (jnp.isnan(vb) & ~jnp.isnan(va))
    return better


def _pair_merge(op, a, b, nat: bool = False):
    """Sequential form of the mesh collectives (parallel/mapreduce.py):
    psum -> add, pmax -> maximum, the var triple -> the Chan update
    (reference _var_combine, aggregations.py:392-451). ``nat`` re-injects
    the NaT marker through min/max exactly as _combine_simple does."""
    import jax.numpy as jnp

    if op in ("max", "min") and nat and jnp.issubdtype(a.dtype, jnp.signedinteger):
        # the signedinteger guard matches _combine_simple
        # (parallel/mapreduce.py): bool intermediates (the 'all'/'any'
        # combines) must NOT compare against the int64 marker — the cast
        # marker is True and would absorb every merge
        m = jnp.maximum(a, b) if op == "max" else jnp.minimum(a, b)
        marker = jnp.asarray(np.iinfo(np.int64).min, a.dtype)
        return jnp.where((a == marker) | (b == marker), marker, m)
    if op == "var":
        m2a, ta, na = a.arrays
        m2b, tb, nb = b.arrays
        nab = na + nb
        tab = ta + tb
        mua = ta / jnp.where(na > 0, na, 1)
        mub = tb / jnp.where(nb > 0, nb, 1)
        muab = tab / jnp.where(nab > 0, nab, 1)
        m2 = m2a + m2b + na * (mua - muab) ** 2 + nb * (mub - muab) ** 2
        return MultiArray((m2, tab, nab))
    if op == "sum":
        return a + b
    if op == "prod":
        return a * b
    if op == "max":
        return jnp.maximum(a, b)
    if op == "min":
        return jnp.minimum(a, b)
    if callable(op):
        # the mesh contract: op(stacked) over the shard axis — here the
        # "shards" are the two accumulation halves; leaf-wise for pytrees
        if isinstance(a, MultiArray):
            return op(
                MultiArray(tuple(jnp.stack([x, y]) for x, y in zip(a.arrays, b.arrays)))
            )
        return op(jnp.stack([a, b]))
    raise NotImplementedError(f"streaming merge for combine op {op!r}")
