"""xrlite: a minimal labeled-array layer (DataArray/Dataset) for the adapter.

The reference's top layer is an xarray adapter (/root/reference/flox/xarray.py)
— but xarray is an *optional* dependency there, and may be absent here too.
This module provides the small slice of labeled-array semantics that
``flox_tpu.xarray.xarray_reduce`` needs — named dims, coords, attrs,
``broadcast``, ``expand_dims``, and an ``apply_ufunc`` with core-dim
handling — with xarray-compatible call signatures. The adapter binds to
real xarray when it is installed and to xrlite otherwise, so the SAME
adapter code path is exercised either way.

Design notes (not a port of xarray):

* Arrays stay whatever they are (numpy or jax.Array); nothing here forces a
  host copy, so a jit-produced result can flow through labeled ops.
* No index alignment/joins — the adapter's contract is "already aligned",
  which is also what it requests from real xarray (``join="exact"``).
* Coordinates may hold ``pd.Index``/``pd.MultiIndex`` objects directly;
  grouping by a MultiIndex level-product works through the same path as the
  reference's PandasMultiIndex handling (xarray.py:263-269, 468-479).
"""

from __future__ import annotations

from typing import Any, Hashable, Mapping, Sequence

import numpy as np
import pandas as pd

__all__ = ["DataArray", "Dataset", "broadcast", "apply_ufunc"]


def _as_values(obj):
    if isinstance(obj, (pd.Index, pd.Series)):
        return obj
    return obj


class DataArray:
    """A named, dim-labeled array with coords and attrs (xarray subset)."""

    __slots__ = ("data", "dims", "_coords", "attrs", "name")

    def __init__(
        self,
        data,
        dims: Sequence[Hashable] | None = None,
        coords: Mapping[Hashable, Any] | None = None,
        name: Hashable | None = None,
        attrs: dict | None = None,
    ):
        if isinstance(data, DataArray):
            coords = {**data.coords, **(coords or {})}
            dims = dims if dims is not None else data.dims
            name = name if name is not None else data.name
            attrs = attrs if attrs is not None else dict(data.attrs)
            data = data.data
        self.data = data
        nd = np.ndim(data)
        if dims is None:
            dims = tuple(f"dim_{i}" for i in range(nd))
        dims = (dims,) if isinstance(dims, str) else tuple(dims)
        if len(dims) != nd:
            raise ValueError(f"{len(dims)} dims {dims} for {nd}-d data")
        self.dims = dims
        self.attrs = dict(attrs or {})
        self.name = name
        self._coords: dict[Hashable, tuple[tuple[Hashable, ...], Any]] = {}
        for cname, cval in (coords or {}).items():
            self._set_coord(cname, cval)

    # -- construction helpers ------------------------------------------------

    def _set_coord(self, cname, cval):
        if isinstance(cval, DataArray):
            self._coords[cname] = (cval.dims, cval.data)
        elif isinstance(cval, tuple) and len(cval) == 2 and not isinstance(cval[0], int):
            cdims, cdata = cval
            cdims = (cdims,) if isinstance(cdims, str) else tuple(cdims)
            self._coords[cname] = (cdims, _as_values(cdata))
        elif isinstance(cval, (pd.Index, pd.MultiIndex)):
            self._coords[cname] = ((cname,), cval)
        else:
            arr = np.asarray(cval)
            if arr.ndim == 0:
                self._coords[cname] = ((), arr)
            else:
                self._coords[cname] = ((cname,), arr)
        cdims, cdata = self._coords[cname]
        for d, n in zip(cdims, np.shape(cdata)):
            if d in self.dims and n != self.sizes[d]:
                raise ValueError(
                    f"coord {cname!r} has size {n} along {d!r}; data has {self.sizes[d]}"
                )

    # -- xarray-compatible surface ------------------------------------------

    @property
    def coords(self) -> dict[Hashable, "DataArray"]:
        return {
            k: DataArray(v, dims=d, name=k) for k, (d, v) in self._coords.items()
        }

    @property
    def values(self) -> np.ndarray:
        return np.asarray(self.data)

    @property
    def shape(self) -> tuple[int, ...]:
        return np.shape(self.data)

    @property
    def dtype(self):
        return getattr(self.data, "dtype", np.asarray(self.data).dtype)

    @property
    def ndim(self) -> int:
        return len(self.dims)

    @property
    def sizes(self) -> dict[Hashable, int]:
        return dict(zip(self.dims, np.shape(self.data)))

    def get_axis_num(self, dim: Hashable) -> int:
        return self.dims.index(dim)

    def __getitem__(self, key):
        if key in self._coords:
            d, v = self._coords[key]
            return DataArray(v, dims=d, name=key)
        raise KeyError(key)

    def __contains__(self, key) -> bool:
        return key in self._coords

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (
            f"<xrlite.DataArray {self.name or ''} {tuple(self.dims)} "
            f"shape={self.shape} dtype={self.dtype}>"
        )

    def copy(self) -> "DataArray":
        out = DataArray(self.data, dims=self.dims, name=self.name, attrs=dict(self.attrs))
        out._coords = dict(self._coords)
        return out

    def rename(self, name: Hashable) -> "DataArray":
        out = self.copy()
        out.name = name
        return out

    def transpose(self, *dims: Hashable) -> "DataArray":
        if not dims:
            dims = tuple(reversed(self.dims))
        missing = [d for d in dims if d not in self.dims]
        if missing:
            raise ValueError(f"transpose: dims {missing} not in {self.dims}")
        order = [self.dims.index(d) for d in dims]
        data = self.data
        if order != list(range(len(order))):
            if isinstance(data, pd.Index):
                data = np.asarray(data)  # MultiIndex -> object array of tuples
            data = data.transpose(order) if _is_jax(data) else np.transpose(data, order)
        out = DataArray(data, dims=dims, name=self.name, attrs=dict(self.attrs))
        out._coords = dict(self._coords)
        return out

    def expand_dims(self, dim: Mapping[Hashable, int]) -> "DataArray":
        """Prepend new dims of the given sizes (broadcast, zero-copy)."""
        new_dims = tuple(dim) + self.dims
        target = tuple(dim.values()) + np.shape(self.data)
        data = self.data
        if _is_jax(data):
            import jax.numpy as jnp

            data = jnp.broadcast_to(data.reshape((1,) * len(dim) + data.shape), target)
        else:
            data = np.broadcast_to(np.reshape(data, (1,) * len(dim) + np.shape(data)), target)
        out = DataArray(data, dims=new_dims, name=self.name, attrs=dict(self.attrs))
        out._coords = dict(self._coords)
        return out

    def assign_coords(self, coords: Mapping[Hashable, Any]) -> "DataArray":
        out = self.copy()
        for k, v in coords.items():
            out._set_coord(k, v)
        return out

    def drop_vars(self, names) -> "DataArray":
        names = {names} if isinstance(names, str) else set(names)
        out = self.copy()
        for n in names:
            out._coords.pop(n, None)
        return out


class Dataset:
    """A dict of DataArrays sharing dims/coords (xarray subset)."""

    __slots__ = ("_vars", "_coords", "attrs")

    def __init__(
        self,
        data_vars: Mapping[Hashable, Any] | None = None,
        coords: Mapping[Hashable, Any] | None = None,
        attrs: dict | None = None,
    ):
        self._vars: dict[Hashable, DataArray] = {}
        self._coords: dict[Hashable, tuple[tuple[Hashable, ...], Any]] = {}
        self.attrs = dict(attrs or {})
        for cname, cval in (coords or {}).items():
            probe = DataArray(0.0)  # reuse coord normalization
            probe.dims = ()
            probe._set_coord(cname, cval)
            self._coords[cname] = probe._coords[cname]
        for name, var in (data_vars or {}).items():
            self[name] = var

    @property
    def data_vars(self) -> dict[Hashable, DataArray]:
        return dict(self._vars)

    @property
    def coords(self) -> dict[Hashable, DataArray]:
        return {k: DataArray(v, dims=d, name=k) for k, (d, v) in self._coords.items()}

    @property
    def dims(self) -> dict[Hashable, int]:
        out: dict[Hashable, int] = {}
        for var in self._vars.values():
            out.update(var.sizes)
        return out

    sizes = dims

    def __contains__(self, key) -> bool:
        return key in self._vars or key in self._coords

    def __iter__(self):
        return iter(self._vars)

    def __getitem__(self, key) -> DataArray:
        if key in self._vars:
            var = self._vars[key].copy()
            for cname, (cdims, cdata) in self._coords.items():
                if all(d in var.dims for d in cdims):
                    var._coords.setdefault(cname, (cdims, cdata))
            return var
        if key in self._coords:
            d, v = self._coords[key]
            return DataArray(v, dims=d, name=key)
        raise KeyError(key)

    def __setitem__(self, key, value) -> None:
        if isinstance(value, tuple) and len(value) == 2 and not isinstance(value[0], int):
            value = DataArray(value[1], dims=value[0], name=key)
        if not isinstance(value, DataArray):
            value = DataArray(value, name=key)
        var = value.copy()
        var.name = key
        # hoist the variable's coords to the dataset
        for cname, cv in var._coords.items():
            self._coords.setdefault(cname, cv)
        var._coords = {}
        self._vars[key] = var

    def drop_vars(self, names) -> "Dataset":
        names = {names} if isinstance(names, str) else set(names)
        out = Dataset(attrs=dict(self.attrs))
        out._coords = {k: v for k, v in self._coords.items() if k not in names}
        out._vars = {k: v.copy() for k, v in self._vars.items() if k not in names}
        return out

    def assign_coords(self, coords: Mapping[Hashable, Any]) -> "Dataset":
        out = Dataset(attrs=dict(self.attrs))
        out._vars = {k: v.copy() for k, v in self._vars.items()}
        out._coords = dict(self._coords)
        probe = DataArray(0.0)
        probe.dims = ()
        for k, v in coords.items():
            probe._coords = {}
            probe._set_coord(k, v)
            out._coords[k] = probe._coords[k]
        return out

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"<xrlite.Dataset vars={list(self._vars)} dims={self.dims}>"


def _is_jax(x) -> bool:
    try:
        import jax

        return isinstance(x, jax.Array)
    except ImportError:  # pragma: no cover
        return False


def broadcast(*arrays: DataArray) -> tuple[DataArray, ...]:
    """Broadcast DataArrays against each other by dim name (xarray subset:
    no index alignment — inputs are assumed aligned, as with join='exact')."""
    all_dims: dict[Hashable, int] = {}
    for a in arrays:
        for d, n in a.sizes.items():
            if d in all_dims and all_dims[d] != n:
                raise ValueError(
                    f"conflicting sizes for dim {d!r}: {all_dims[d]} vs {n}"
                )
            all_dims.setdefault(d, n)
    order = tuple(all_dims)
    out = []
    for a in arrays:
        missing = {d: all_dims[d] for d in order if d not in a.dims}
        b = a.expand_dims(missing) if missing else a
        b = b.transpose(*order)
        out.append(b)
    return tuple(out)


def apply_ufunc(
    func,
    *args,
    input_core_dims: Sequence[Sequence[Hashable]] | None = None,
    output_core_dims: Sequence[Sequence[Hashable]] | None = None,
    keep_attrs: bool = True,
    dask: str = "forbidden",
    vectorize: bool = False,
    join: str = "exact",
    dataset_fill_value=None,
    **_ignored,
):
    """Core-dims apply (the slice of xr.apply_ufunc the adapter uses).

    Each arg's core dims are moved to the end (in the given order); broadcast
    (non-core) dims are aligned by name across args; ``func`` gets the raw
    arrays and its result is re-wrapped with dims = broadcast + output core.
    """
    if input_core_dims is None:
        input_core_dims = [()] * len(args)
    if output_core_dims is None:
        output_core_dims = [()]
    das = [a if isinstance(a, DataArray) else DataArray(a) for a in args]

    # broadcast dims: every non-core dim, in order of first appearance
    bcast: dict[Hashable, int] = {}
    for a, core in zip(das, input_core_dims):
        for d, n in a.sizes.items():
            if d not in core:
                if d in bcast and bcast[d] != n:
                    raise ValueError(f"conflicting sizes for dim {d!r}")
                bcast.setdefault(d, n)
    border = tuple(bcast)

    raws = []
    for a, core in zip(das, input_core_dims):
        missing_b = {d: bcast[d] for d in border if d not in a.dims}
        b = a.expand_dims(missing_b) if missing_b else a
        b = b.transpose(*(border + tuple(core)))
        raws.append(b.data)

    result = func(*raws)
    results = result if isinstance(result, tuple) else (result,)
    if len(results) != len(output_core_dims):
        raise ValueError(
            f"func returned {len(results)} outputs; expected {len(output_core_dims)}"
        )

    outs = []
    template = das[0]
    for res, ocore in zip(results, output_core_dims):
        dims = border + tuple(ocore)
        out = DataArray(res, dims=dims, name=template.name,
                        attrs=dict(template.attrs) if keep_attrs else {})
        # carry coords that still apply (all their dims survive)
        for cname, (cdims, cdata) in template._coords.items():
            if all(d in dims for d in cdims):
                out._coords[cname] = (cdims, cdata)
        outs.append(out)
    return tuple(outs) if isinstance(result, tuple) else outs[0]
