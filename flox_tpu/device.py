"""Fully-traceable device-resident groupby (L4, jit-composable surface).

The main ``groupby_reduce`` keeps unknown-label discovery host-side, like
the reference. When ``expected_groups`` is known, NOTHING needs the host:
factorization is a ``searchsorted`` (factorize.factorize_device), the
reduction is the kernel bundle, and the whole pipeline is one traceable
function users can place inside their own ``jax.jit`` / ``shard_map`` /
training step — the capability the reference cannot offer (its engines are
host numpy).

This realizes the "device-resident integer group codes" design point of the
build plan (SURVEY.md §7 step 2; reference counterpart factorize.py:42-99
is host-only).
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

from . import factorize as fct
from .aggregations import _initialize_aggregation

__all__ = ["groupby_reduce_device", "codes_device", "memory_stats", "reinitialize"]


def reinitialize() -> bool:
    """Tear down and re-create the JAX backend client — the recovery step
    after a device-loss classification (``resilience.DEVICE_LOST``).

    Clears jax's live backend clients so the next dispatch re-initializes
    the runtime (PJRT re-enumerates devices), and drops this package's
    compiled-program caches — executables compiled against the dead client
    hold dangling device references and must never be served again. The
    metrics registry, cost ledger, and autotune store are deliberately
    untouched: recovery is not a stats reset. Returns whether a backend
    teardown API was found (``False`` degrades to cache-drop-only, which is
    still the correct half of the story on backends that self-heal).
    Never raises: recovery must be drivable from an error path.
    """
    import jax

    torn_down = False
    # the teardown API moved across jax releases; try each spelling
    holders = (
        getattr(getattr(jax, "extend", None), "backend", None),
        getattr(jax, "_src", None) and getattr(jax._src, "api", None),
        jax,
    )
    for holder in holders:
        fn = getattr(holder, "clear_backends", None) if holder is not None else None
        if callable(fn) and _teardown_quietly(fn):
            torn_down = True
            break
    try:
        from .core import _jitted_bundle
        from .fusion import _FUSED_PROGRAM_CACHE
        from .parallel.mapreduce import _PROGRAM_CACHE
        from .parallel.scan import _SCAN_CACHE
        from .streaming import _STEP_CACHE

        _jitted_bundle.cache_clear()
        _PROGRAM_CACHE.clear()
        _SCAN_CACHE.clear()
        _STEP_CACHE.clear()
        _FUSED_PROGRAM_CACHE.clear()
    except Exception:  # noqa: BLE001 — partial recovery beats masking the loss
        pass
    return torn_down


def memory_stats(devices: Sequence | None = None) -> dict[str, int] | None:
    """Aggregate allocator statistics across the local devices.

    Returns ``{"bytes_in_use", "peak_bytes_in_use", "devices",
    "bytes_limit"}`` summed over every local device that reports stats
    (``peak`` falls back to ``bytes_in_use`` for allocators that track no
    peak; ``bytes_limit`` is the per-device HBM capacity summed the same
    way, ``None`` when no reporting device exposes one — the in-use gauges
    then stay unitless rather than inventing a denominator), or ``None``
    when no device reports any — CPU backends commonly return nothing, and
    the telemetry HBM gauges (``telemetry.sample_hbm``) simply stay absent
    there. Never raises: observability must not take a dispatch down.
    """
    import jax

    try:
        devs = list(jax.local_devices()) if devices is None else list(devices)
    except Exception:  # noqa: BLE001 — no backend at all
        return None
    in_use = peak = limit = 0
    reporting = limit_reporting = 0
    for dev in devs:
        stats = _device_stats(dev)
        if not stats:
            continue
        reporting += 1
        dev_in_use = int(stats.get("bytes_in_use", 0))
        in_use += dev_in_use
        peak += int(stats.get("peak_bytes_in_use", dev_in_use))
        dev_limit = stats.get("bytes_limit")
        if dev_limit:
            limit_reporting += 1
            limit += int(dev_limit)
    if not reporting:
        return None
    return {
        "bytes_in_use": in_use,
        "peak_bytes_in_use": peak,
        "devices": reporting,
        "bytes_limit": limit if limit_reporting else None,
    }


def _teardown_quietly(fn: Any) -> bool:
    """Run one backend-teardown candidate; ``False`` means try the next
    spelling (recovery proceeds to the cache drop either way)."""
    import warnings

    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            fn()
        return True
    except Exception:  # noqa: BLE001 — an unavailable spelling, not a fault
        return False


def _device_stats(dev: Any) -> dict | None:
    """One device's allocator stats, or None where the backend has none."""
    stats = getattr(dev, "memory_stats", None)
    try:
        return stats() if callable(stats) else None
    except Exception:  # noqa: BLE001 — a backend without the query
        return None


def codes_device(
    by: Any,
    expected_values: Sequence | None = None,
    *,
    bins: Sequence | None = None,
    closed: str = "right",
) -> Any:
    """Traceable label -> dense code computation on device.

    Exactly one of ``expected_values`` (sorted unique labels) or ``bins``
    (interval edges) must be given. Returns int32 codes with -1 = missing.
    """
    if (expected_values is None) == (bins is None):
        raise ValueError("Pass exactly one of expected_values or bins")
    if bins is not None:
        return fct.bin_device(by, bins, closed=closed)
    return fct.factorize_device(by, expected_values)


def groupby_reduce_device(
    array: Any,
    *by: Any,
    func: str,
    expected_values: Sequence | None = None,
    bins: Sequence | None = None,
    fill_value: Any = None,
    dtype: Any = None,
    finalize_kwargs: dict | None = None,
) -> Any:
    """Grouped reduction with every step on device — safe inside ``jax.jit``.

    ``by`` entries are device arrays whose *flattened* elements align with
    the trailing dims of ``array``; ``expected_values`` / ``bins`` give the
    static group space (one entry per ``by``; a bare array is accepted for
    one grouper). Reduces over all ``by`` dims. Returns the dense result
    (..., *group_sizes) — no groups tuple (they are exactly the expected
    values, which the caller already has).

    Limitations vs the host orchestrator: no unknown-label discovery, no
    partial-axis reduction, no datetime round-trips — those need the host.
    """
    import jax.numpy as jnp

    from .kernels import generic_kernel

    nby = len(by)
    if nby == 0:
        raise TypeError("Must pass at least one `by`")

    def _norm(spec):
        if spec is None:
            return (None,) * nby
        if nby == 1:
            # a bare array OR a plain list of group values is one spec;
            # only a 1-tuple is the explicit per-grouper form
            if isinstance(spec, tuple) and len(spec) == 1:
                return spec
            return (spec,)
        if not isinstance(spec, (tuple, list)) or len(spec) != nby:
            raise ValueError(
                f"With {nby} groupers, pass a tuple of {nby} expected_values/bins entries"
            )
        return tuple(spec)

    expected_t = _norm(expected_values)
    bins_t = _norm(bins)

    codes_list = []
    sizes = []
    for b, exp, edges in zip(by, expected_t, bins_t):
        flat = jnp.asarray(b).reshape(-1)
        if edges is not None:
            codes_list.append(fct.bin_device(flat, edges))
            sizes.append(len(edges) - 1)
        elif exp is not None:
            codes_list.append(fct.factorize_device(flat, exp))
            sizes.append(len(exp))
        else:
            raise ValueError("groupby_reduce_device needs expected_values or bins per `by`")

    # ravel multi-by codes on device; any -1 component -> -1
    codes = codes_list[0]
    size = sizes[0]
    for c, s in zip(codes_list[1:], sizes[1:]):
        missing = (codes < 0) | (c < 0)
        codes = jnp.where(missing, -1, codes * s + c)
        size *= s

    arr = jnp.asarray(array)
    n = codes.shape[0]
    lead = arr.shape[: arr.ndim - _span_ndim(arr.shape, n)]
    arr_flat = arr.reshape(lead + (n,))

    agg = _initialize_aggregation(
        func, dtype, np.dtype(str(arr.dtype)), fill_value, 0, finalize_kwargs
    )
    kw = dict(agg.finalize_kwargs)
    kernel_dtype = None
    if agg.name in ("sum", "nansum", "prod", "nanprod", "mean", "nanmean",
                    "var", "nanvar", "std", "nanstd") or dtype is not None:
        kernel_dtype = np.dtype(agg.final_dtype)
        if not _x64():
            # don't request 64-bit accumulation the backend cannot represent
            if kernel_dtype.itemsize == 8 and kernel_dtype.kind in "fiu":
                kernel_dtype = np.dtype(kernel_dtype.kind + "4")
    result = generic_kernel(
        agg.numpy[0] if isinstance(agg.numpy[0], str) else func,
        codes,
        arr_flat,
        size=size,
        fill_value=agg.final_fill_value if not _is_sentinel(agg.final_fill_value) else None,
        dtype=kernel_dtype,
        **kw,
    )
    if kernel_dtype is not None and result.dtype != kernel_dtype:
        result = result.astype(kernel_dtype)
    new_dims = agg.new_dims()
    out_shape = new_dims + lead + tuple(sizes)
    return result.reshape(out_shape)


def _span_ndim(shape: tuple[int, ...], n: int) -> int:
    """How many trailing dims of ``shape`` flatten to ``n`` elements."""
    prod = 1
    for i, s in enumerate(reversed(shape), start=1):
        prod *= s
        if prod == n:
            return i
    raise ValueError(f"`by` length {n} does not match trailing dims of array shape {shape}")


def _x64() -> bool:
    from . import utils

    return utils.x64_enabled()


def _is_sentinel(v) -> bool:
    from . import dtypes

    return v in (dtypes.NA, dtypes.INF, dtypes.NINF)
