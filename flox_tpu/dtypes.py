"""Dtype promotion and fill-value resolution (L0).

TPU-native rethink of the reference's dtype utilities
(/root/reference/flox/xrdtypes.py:9-209): the same *semantics* — sentinel
fill-value placeholders resolved per-dtype, NA-driven promotion, datetime
handling — but organized around what XLA needs: every fill value must be a
concrete scalar at trace time (no object dtype on device), and float64 use is
gated on ``jax.config.jax_enable_x64``.
"""

from __future__ import annotations

import functools
from typing import Any

import numpy as np

__all__ = [
    "INF",
    "NINF",
    "NA",
    "get_fill_value",
    "get_pos_infinity",
    "get_neg_infinity",
    "maybe_promote",
    "is_datetime_like",
    "dtype_to_view",
    "normalize_dtype",
]


class _Sentinel:
    """Placeholder fill value resolved against a concrete dtype later.

    Mirrors the role of the reference's AlwaysGreaterThan/AlwaysLessThan/NA
    trio (xrdtypes.py:9-32) without the rich-comparison machinery: on TPU the
    sentinel never reaches a kernel — it is resolved to a scalar before trace.
    """

    __slots__ = ("_name",)

    def __init__(self, name: str) -> None:
        self._name = name

    def __repr__(self) -> str:
        return f"<{self._name}>"

    # Sentinels are singletons compared by identity; they must survive the
    # deepcopy that _initialize_aggregation applies to registry templates.
    def __copy__(self) -> "_Sentinel":
        return self

    def __deepcopy__(self, memo) -> "_Sentinel":
        return self


#: Resolves to the greatest representable value of the target dtype.
INF = _Sentinel("INF")
#: Resolves to the least representable value of the target dtype.
NINF = _Sentinel("NINF")
#: Resolves to the missing-value marker of the target dtype (NaN/NaT/...).
NA = _Sentinel("NA")


def is_datetime_like(dtype: np.dtype) -> bool:
    return np.issubdtype(dtype, np.datetime64) or np.issubdtype(dtype, np.timedelta64)


def dtype_to_view(dtype: np.dtype) -> np.dtype:
    """Device-representable view dtype: datetimes become int64 on device."""
    dtype = np.dtype(dtype)
    if is_datetime_like(dtype):
        return np.dtype("int64")
    return dtype


def get_pos_infinity(dtype: np.dtype, max_for_int: bool = False) -> Any:
    """Largest value usable as a '+inf' fill for ``dtype``.

    Parity: xrdtypes.get_pos_infinity (xrdtypes.py:97-124). For integers the
    caller chooses between true inf (promoting) and ``iinfo.max``
    (dtype-preserving, what segment_min identity needs on device).
    """
    dtype = np.dtype(dtype)
    if np.issubdtype(dtype, np.floating):
        return dtype.type(np.inf)
    if np.issubdtype(dtype, np.integer):
        return np.iinfo(dtype).max if max_for_int else np.inf
    if np.issubdtype(dtype, np.complexfloating):
        return dtype.type(np.inf + 0j)
    if is_datetime_like(dtype):
        return np.iinfo(np.int64).max
    if dtype.kind == "b":
        return True
    return np.inf


def get_neg_infinity(dtype: np.dtype, min_for_int: bool = False) -> Any:
    """Mirror of :func:`get_pos_infinity` (xrdtypes.py:127-154)."""
    dtype = np.dtype(dtype)
    if np.issubdtype(dtype, np.floating):
        return dtype.type(-np.inf)
    if np.issubdtype(dtype, np.integer):
        return np.iinfo(dtype).min if min_for_int else -np.inf
    if np.issubdtype(dtype, np.complexfloating):
        return dtype.type(-np.inf + 0j)
    if is_datetime_like(dtype):
        return np.iinfo(np.int64).min
    if dtype.kind == "b":
        return False
    return -np.inf


def maybe_promote(dtype: np.dtype) -> tuple[np.dtype, Any]:
    """Promote ``dtype`` so it can hold a missing value; return (dtype, NA).

    Parity: xrdtypes.maybe_promote (xrdtypes.py:35-77). Integers promote to
    float64 (float32 stays float32), datetimes use NaT, bools promote to
    object in xarray but here to float64 — object dtype cannot exist on
    device.
    """
    dtype = np.dtype(dtype)
    if np.issubdtype(dtype, np.floating):
        return dtype, dtype.type(np.nan)
    if np.issubdtype(dtype, np.complexfloating):
        return dtype, dtype.type(np.nan + np.nan * 1j)
    if np.issubdtype(dtype, np.integer):
        promoted = np.dtype("float32") if dtype.itemsize <= 2 else np.dtype("float64")
        return promoted, promoted.type(np.nan)
    if np.issubdtype(dtype, np.datetime64):
        return dtype, np.datetime64("NaT")
    if np.issubdtype(dtype, np.timedelta64):
        return dtype, np.timedelta64("NaT")
    if dtype.kind == "b":
        return np.dtype("float64"), np.nan
    return np.dtype("object"), np.nan


def get_fill_value(dtype: np.dtype, fill_value: Any) -> Any:
    """Resolve a sentinel (or passthrough) fill value against ``dtype``.

    Parity: xrdtypes._get_fill_value (xrdtypes.py:188-209).
    """
    if fill_value is INF or (fill_value is None and np.dtype(dtype).kind not in "fcmM"):
        return get_pos_infinity(dtype, max_for_int=True)
    if fill_value is NINF:
        return get_neg_infinity(dtype, min_for_int=True)
    if fill_value is NA or fill_value is None:
        dtype = np.dtype(dtype)
        if np.issubdtype(dtype, np.floating) or np.issubdtype(dtype, np.complexfloating):
            return dtype.type(np.nan)
        if np.issubdtype(dtype, np.datetime64):
            return np.datetime64("NaT")
        if np.issubdtype(dtype, np.timedelta64):
            return np.timedelta64("NaT")
        # Caller should have promoted already; be safe for ints/bool.
        return np.nan
    return fill_value


@functools.lru_cache(maxsize=None)
def _result_type_cached(*dtypes: np.dtype) -> np.dtype:
    return np.result_type(*dtypes)


def normalize_dtype(
    dtype: Any,
    array_dtype: np.dtype,
    preserves_dtype: bool = False,
    fill_value: Any = None,
) -> np.dtype:
    """Decide the output dtype of an aggregation.

    Parity: xrdtypes._normalize_dtype (xrdtypes.py:153-172): explicit request
    wins; dtype-preserving aggs keep the input dtype; sum-like aggs promote
    small ints per numpy rules; an NaN-ish fill value forces a float-capable
    dtype.
    """
    array_dtype = np.dtype(array_dtype)
    if dtype is None:
        if preserves_dtype:
            dtype = array_dtype
        elif array_dtype.kind in "iub":
            # numpy promotes small ints to the default int for sums.
            dtype = _result_type_cached(array_dtype, np.dtype(np.int_))
        else:
            dtype = array_dtype
    dtype = np.dtype(dtype)
    if fill_value not in (None, INF, NINF, NA) and np.issubdtype(type(fill_value), np.floating):
        if not (
            np.issubdtype(dtype, np.floating) or np.issubdtype(dtype, np.complexfloating)
        ) and np.isnan(fill_value):
            dtype = np.result_type(dtype, np.float64)
    return dtype
