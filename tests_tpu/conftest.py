"""On-chip verification suite — runs against the REAL accelerator.

The main suite (``tests/``) pins the CPU platform and x64 so every result is
comparable bit-for-bit with float64 numpy oracles — the reference's
sync-scheduler strategy (reference tests/test_core.py:65). This directory is
the other leg: the same kernels exercised on actual TPU hardware, at f32
tolerances, including the Pallas/MXU lowerings that interpret mode cannot
validate (VERDICT r1 weak #2).

Run manually when the chip is reachable:

    python -m pytest tests_tpu/ -q

Every test is skipped (not failed) when no accelerator responds within the
probe timeout, so this suite is safe to include in any environment.
"""

import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def _accelerator_responsive(timeout_s: float = 60.0) -> bool:
    """Probe device init in a subprocess — a wedged TPU tunnel blocks forever
    in C, so an in-process jax.devices() could hang the whole run."""
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; assert jax.devices()[0].platform != 'cpu'"],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        return proc.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        return False


_RESPONSIVE = None


def pytest_collection_modifyitems(config, items):
    global _RESPONSIVE
    # this hook sees the whole session's items; only gate OUR directory, or
    # a combined `pytest tests tests_tpu` run would skip the CPU suite too
    here = os.path.dirname(os.path.abspath(__file__))
    ours = [i for i in items if str(getattr(i, "path", "")).startswith(here)]
    if not ours:
        return
    if _RESPONSIVE is None:
        _RESPONSIVE = _accelerator_responsive()
    if not _RESPONSIVE:
        marker = pytest.mark.skip(reason="no responsive accelerator (TPU tunnel down)")
        for item in ours:
            item.add_marker(marker)


@pytest.fixture(scope="session")
def tpu():
    import jax

    dev = jax.devices()[0]
    assert dev.platform != "cpu"
    return dev
