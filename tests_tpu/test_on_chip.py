"""Kernel + end-to-end verification on real TPU hardware.

The CPU suite proves semantics against float64 oracles; this suite proves the
actual TPU lowerings — Mosaic/Pallas tiling, MXU one-hot GEMMs, f32 scatter —
compute the same answers at f32 tolerances (VERDICT r1: "the TPU legs of the
test suite have never executed on hardware").
"""

import numpy as np
import pytest

RNG = np.random.default_rng(7)
RTOL, ATOL = 1e-5, 1e-5


def _oracle(func, values, codes, size, **kw):
    np_func = getattr(np, func)
    out = []
    for g in range(size):
        grp = values[..., codes == g].astype(np.float64)
        with np.errstate(invalid="ignore"), np.testing.suppress_warnings() as sup:
            sup.filter(RuntimeWarning)
            res = (
                np.full(values.shape[:-1], np.nan)
                if grp.shape[-1] == 0
                else np_func(grp, axis=-1, **kw)
            )
        out.append(res)
    return np.stack(out, axis=-1)


@pytest.fixture(scope="module")
def data():
    n, size = 1003, 7
    codes = RNG.integers(-1, size, n).astype(np.int32)
    values = RNG.normal(size=(5, n)).astype(np.float32)
    values[RNG.random((5, n)) < 0.05] = np.nan
    return values, codes, size


FUNCS = [
    "nansum", "nanmean", "nanmax", "nanmin", "nanvar", "nanstd",
    "nanmedian", "nanprod",
]


@pytest.mark.parametrize("func", FUNCS)
def test_kernels_match_f64_oracle(tpu, data, func):
    from flox_tpu.kernels import generic_kernel

    values, codes, size = data
    got = np.asarray(generic_kernel(func, codes, values, size=size, fill_value=np.nan))
    want = _oracle(func, values, codes, size)
    np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL, equal_nan=True)


@pytest.mark.parametrize("impl", ["scatter", "matmul", "pallas"])
def test_segment_sum_impls_agree(tpu, data, impl):
    """Every lowering of the hot op must produce the same sums on chip."""
    import jax.numpy as jnp

    from flox_tpu.kernels import generic_kernel
    from flox_tpu.options import OPTIONS, set_options

    values, codes, size = data
    want = _oracle("nansum", values, codes, size)
    before = OPTIONS["segment_sum_impl"]
    with set_options(segment_sum_impl=impl):
        got = np.asarray(
            generic_kernel("nansum", codes, jnp.asarray(values), size=size, fill_value=np.nan)
        )
    np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL, equal_nan=True)
    assert OPTIONS["segment_sum_impl"] == before  # context manager restored it


def test_pallas_ragged_nonfinite(tpu):
    """Non-divisible block shapes + IEEE propagation + missing labels on the
    real Mosaic lowering (interpret mode cannot validate this)."""
    import jax.numpy as jnp

    from flox_tpu.pallas_kernels import segment_sum_pallas

    n, k, size = 3001, 517, 13
    vals = RNG.normal(size=(n, k)).astype(np.float32)
    vals[RNG.random((n, k)) < 0.01] = np.nan
    vals[RNG.random((n, k)) < 0.005] = np.inf
    vals[RNG.random((n, k)) < 0.005] = -np.inf
    codes = RNG.integers(-1, size, n).astype(np.int32)
    got = np.asarray(segment_sum_pallas(jnp.asarray(vals), jnp.asarray(codes), size))
    ref = np.stack([vals[codes == g].astype(np.float64).sum(0) for g in range(size)])
    finite = np.isfinite(ref)
    np.testing.assert_allclose(got[finite], ref[finite], rtol=1e-4, atol=1e-4)
    assert (np.isnan(got) == np.isnan(ref)).all()
    assert (np.isposinf(got) == np.isposinf(ref)).all()
    assert (np.isneginf(got) == np.isneginf(ref)).all()


def test_pallas_moveaxis_consumes_buffer_in_place(tpu):
    """The (…, N) trailing-reduce layout must flow through the kernel via the
    cancelled double-transpose (correctness here; OOM-avoidance at scale)."""
    import jax.numpy as jnp

    from flox_tpu.pallas_kernels import segment_sum_pallas

    n, k, size = 2048, 300, 5
    arr = RNG.normal(size=(k, n)).astype(np.float32)
    codes = (np.arange(n) % size).astype(np.int32)
    got = np.asarray(
        segment_sum_pallas(jnp.moveaxis(jnp.asarray(arr), -1, 0), jnp.asarray(codes), size)
    )
    ref = np.stack([arr[:, codes == g].astype(np.float64).sum(1) for g in range(size)])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_bf16_accumulates_f32(tpu):
    """ADVICE r1 (high): bf16 running sums saturate at 256 — counts and sums
    must accumulate in f32 on the MXU's native accumulate path."""
    import jax.numpy as jnp

    from flox_tpu.kernels import generic_kernel

    n = 4096
    # the bf16 input is the point of the test (saturation regression)
    vals = jnp.asarray(np.linspace(0, 1, n, dtype=np.float32)).astype(jnp.bfloat16)  # floxlint: disable=FLX003
    codes = np.zeros(n, dtype=np.int32)
    got = float(np.asarray(generic_kernel("nanmean", codes, vals, size=1))[0])
    assert abs(got - 0.5) < 0.01, got


def test_argreductions_on_chip(tpu, data):
    from flox_tpu.kernels import generic_kernel

    values, codes, size = data
    vals = np.where(np.isnan(values), 0.0, values)  # plain arg* propagate NaN
    got = np.asarray(generic_kernel("argmax", codes, vals, size=size, fill_value=-1))
    for g in range(size):
        members = np.flatnonzero(codes == g)
        want = members[np.argmax(vals[:, members], axis=-1)]
        np.testing.assert_array_equal(got[:, g], want)


def test_quantile_vector_q(tpu, data):
    from flox_tpu.kernels import generic_kernel

    values, codes, size = data
    got = np.asarray(
        generic_kernel("nanquantile", codes, values, size=size, q=[0.25, 0.75])
    )
    want = np.stack(
        [_oracle("nanquantile", values, codes, size, q=q) for q in (0.25, 0.75)]
    )
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4, equal_nan=True)


def test_scans_on_chip(tpu):
    from flox_tpu.kernels import generic_kernel

    n, size = 511, 3
    codes = RNG.integers(0, size, n).astype(np.int32)
    vals = RNG.normal(size=n).astype(np.float32)
    got = np.asarray(generic_kernel("cumsum", codes, vals, size=size))
    want = np.empty(n, np.float64)
    for g in range(size):
        m = codes == g
        want[m] = np.cumsum(vals[m].astype(np.float64))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    vals_nan = vals.copy()
    vals_nan[RNG.random(n) < 0.3] = np.nan
    got_f = np.asarray(generic_kernel("ffill", codes, vals_nan, size=size))
    for g in range(size):
        m = codes == g
        grp = vals_nan[m]
        filled = np.array(grp)
        for i in range(1, len(filled)):
            if np.isnan(filled[i]):
                filled[i] = filled[i - 1]
        np.testing.assert_allclose(got_f[m], filled, rtol=1e-5, equal_nan=True)


def test_pallas_minmax_on_chip(tpu):
    """The VPU select-reduce lowering vs the f64 oracle on real hardware."""
    import jax.numpy as jnp

    from flox_tpu.pallas_kernels import segment_minmax_pallas

    n, k, size = 3001, 517, 13
    vals = RNG.normal(size=(n, k)).astype(np.float32)
    codes = RNG.integers(-1, size, n).astype(np.int32)
    got = np.asarray(segment_minmax_pallas(jnp.asarray(vals), jnp.asarray(codes), size, "max"))
    for g in range(size):
        grp = vals[codes == g]
        want = grp.max(0) if len(grp) else np.full(k, -np.inf, np.float32)
        np.testing.assert_array_equal(got[g], want)


def test_pallas_multistat_on_chip(tpu):
    """The fused multi-statistic megakernel on real hardware: one HBM pass,
    sums bit-identical to segment_sum_pallas (same tiling, same body),
    min/max exact vs the host oracle, NaN markers intact across ragged
    edge blocks."""
    import jax.numpy as jnp

    from flox_tpu.pallas_kernels import segment_multistat_pallas, segment_sum_pallas
    from flox_tpu.utils import reapply_nonfinite

    n, k, size = 3001, 517, 13
    vals = RNG.normal(size=(n, k)).astype(np.float32)
    vals[77, 3] = np.nan
    vals[501, :] = np.nan
    codes = RNG.integers(-1, size, n).astype(np.int32)
    sums, nan_c, pos_c, neg_c, mins, maxs = segment_multistat_pallas(
        jnp.asarray(vals), jnp.asarray(codes), size
    )
    nansum = np.asarray(reapply_nonfinite(sums, nan_c, pos_c, neg_c, skipna=True))
    single = np.asarray(
        segment_sum_pallas(jnp.asarray(vals), jnp.asarray(codes), size, skipna=True)
    )
    np.testing.assert_array_equal(nansum, single)  # bit-identical sums
    for g in range(size):
        grp = vals[codes == g]
        want_min = (
            np.fmin.reduce(grp, axis=0) if len(grp) else np.full(k, np.inf, np.float32)
        )
        want_max = (
            np.fmax.reduce(grp, axis=0) if len(grp) else np.full(k, -np.inf, np.float32)
        )
        np.testing.assert_array_equal(
            np.asarray(mins)[g],
            np.nan_to_num(want_min, nan=np.inf, posinf=np.inf, neginf=-np.inf),
        )
        np.testing.assert_array_equal(
            np.asarray(maxs)[g],
            np.nan_to_num(want_max, nan=-np.inf, posinf=np.inf, neginf=-np.inf),
        )


def test_groupby_aggregate_many_on_chip(tpu):
    """The fused multi-statistic API end-to-end on hardware: every result
    matches its sequential groupby_reduce call bit-for-bit (same lowerings
    under the same policy)."""
    import flox_tpu

    funcs = ("mean", "var", "min", "max", "count")
    vals = RNG.normal(size=(5, 4096)).astype(np.float32)
    vals[0, 17] = np.nan
    codes = RNG.integers(0, 12, 4096)
    out, _ = flox_tpu.groupby_aggregate_many(vals, codes, funcs=funcs, engine="jax")
    for f in funcs:
        seq = flox_tpu.groupby_reduce(vals, codes, func=f, engine="jax")[0]
        np.testing.assert_array_equal(
            np.asarray(out[f]), np.asarray(seq), err_msg=f
        )


def test_pallas_scan_on_chip(tpu):
    """The triangular-matmul grouped cumsum vs a per-group numpy loop on
    real hardware, including NaN poisoning across tile boundaries."""
    import jax.numpy as jnp

    from flox_tpu.pallas_kernels import segment_cumsum_pallas

    n, k, size = 2007, 37, 6
    vals = RNG.normal(size=(n, k)).astype(np.float32)
    vals[777, :] = np.nan
    codes = RNG.integers(-1, size, n).astype(np.int32)
    got = np.asarray(segment_cumsum_pallas(jnp.asarray(vals), jnp.asarray(codes), size, skipna=False))
    for g in range(size):
        m = codes == g
        want = np.cumsum(vals[m].astype(np.float64), axis=0)
        np.testing.assert_allclose(got[m], want, rtol=1e-4, atol=1e-4, equal_nan=True)


def test_groupby_reduce_end_to_end(tpu):
    """Full orchestration (factorize → kernel → finalize) on device arrays."""
    import jax.numpy as jnp

    from flox_tpu import groupby_reduce

    n = 720
    by = np.tile(np.array(["a", "b", "c"]), n // 3)
    vals = jnp.asarray(RNG.normal(size=(4, n)).astype(np.float32))
    result, groups = groupby_reduce(vals, by, func="mean", engine="jax")
    assert list(groups) == ["a", "b", "c"]
    arr = np.asarray(vals)
    for i, g in enumerate(groups):
        np.testing.assert_allclose(
            np.asarray(result)[:, i],
            arr[:, by == g].astype(np.float64).mean(-1),
            rtol=RTOL, atol=ATOL,
        )


def test_groupby_reduce_binned(tpu):
    import pandas as pd

    from flox_tpu import groupby_reduce

    n = 500
    by = RNG.uniform(0, 10, n)
    vals = RNG.normal(size=n).astype(np.float32)
    bins = pd.IntervalIndex.from_breaks([0.0, 2.5, 5.0, 10.0])
    result, groups = groupby_reduce(
        vals, by, func="sum", expected_groups=bins, isbin=True, engine="jax"
    )
    cut = pd.cut(by, bins.left.tolist() + [bins.right[-1]])
    want = pd.Series(vals.astype(np.float64)).groupby(cut, observed=False).sum()
    np.testing.assert_allclose(np.asarray(result), want.to_numpy(), rtol=1e-4, atol=1e-4)


def test_radix_select_quantile_matches_sort_on_chip(tpu):
    # the sort-free order-statistics lowering (radix bisection over MXU
    # segment-sum counts) must agree with the two-key lax.sort path ON THE
    # REAL CHIP — interpret-mode equality does not cover Mosaic/XLA-TPU
    # lowering differences in the counting passes
    import jax.numpy as jnp

    import flox_tpu
    from flox_tpu.kernels import generic_kernel

    n = 26304
    codes = ((np.arange(n) // 24) % 365).astype(np.int32) % 12
    vals = jnp.asarray(RNG.normal(280.0, 10.0, size=(16, n)).astype(np.float32))
    with flox_tpu.set_options(quantile_impl="sort"):
        a = np.asarray(generic_kernel("nanquantile", codes, vals, size=12, q=0.9))
    with flox_tpu.set_options(quantile_impl="select"):
        b = np.asarray(generic_kernel("nanquantile", codes, vals, size=12, q=0.9))
    np.testing.assert_array_equal(a, b)


def test_streaming_runtime_on_chip(tpu):
    # round-5 additions on real hardware: the streaming runtime's jitted
    # per-slab step + the counts-only streaming quantile + the carry-based
    # streaming scan must all match eager ON THE CHIP (real device_put,
    # async dispatch, Mosaic/XLA-TPU lowerings of the slab kernels)
    import flox_tpu
    from flox_tpu.streaming import streaming_groupby_reduce, streaming_groupby_scan

    n = 20_000
    labels = (np.arange(n) // 24) % 12
    vals = RNG.normal(280.0, 10.0, size=(8, n)).astype(np.float32)
    vals[:, ::17] = np.nan

    for func in ("nansum", "nanmean", "nanvar", "nanmax", "first", "nanargmin"):
        eager, _ = flox_tpu.groupby_reduce(vals, labels, func=func)
        got, _ = streaming_groupby_reduce(vals, labels, func=func, batch_len=4096)
        np.testing.assert_allclose(
            np.asarray(got).astype(float), np.asarray(eager).astype(float),
            rtol=RTOL, atol=ATOL, equal_nan=True,
        )

    # streaming quantile: bit-identical to the eager select path on chip
    with flox_tpu.set_options(quantile_impl="select"):
        eager_q, _ = flox_tpu.groupby_reduce(vals, labels, func="nanmedian")
    got_q, _ = streaming_groupby_reduce(vals, labels, func="nanmedian", batch_len=4096)
    np.testing.assert_array_equal(np.asarray(got_q), np.asarray(eager_q))

    # streaming scan: carry across slabs
    eager_s = flox_tpu.groupby_scan(vals[0], labels, func="nancumsum")
    got_s = streaming_groupby_scan(vals[0], labels, func="nancumsum", batch_len=4096)
    np.testing.assert_allclose(
        np.asarray(got_s), np.asarray(eager_s), rtol=RTOL, atol=ATOL, equal_nan=True
    )
