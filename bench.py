"""Headline benchmark: ERA5 hourly -> monthly-mean climatology on one chip.

Metric (BASELINE.json): ERA5 ``groupby('time.month').mean()`` GB/s/chip.
Baseline: the in-repo host numpy engine (``ufunc.at``/bincount — the same
primitive family as the reference's numpy_groupies engine) on the identical
workload. Prints ONE JSON line.

Scale knobs (env):
  FLOX_TPU_BENCH_NLAT / NLON / NTIME — workload shape (default 181x360x26304,
  ~6.8 GB float32: 3 years of hourly steps on a 1-degree grid).
  FLOX_TPU_BENCH_REPS — timed repetitions (default 5).
  FLOX_TPU_BENCH_CHAIN — iterations in the differenced timing chain
  (default 8, min 2; see the timing note in main()).
  FLOX_TPU_BENCH_FORCE_SWEEP — nonempty: run the scatter/matmul/pallas
  impl sweep even on CPU (testing aid; on accelerators it always runs).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _ensure_responsive_backend(timeout_s: float = 90.0) -> bool:
    """Fall back to CPU if the accelerator runtime hangs at device init.

    The TPU tunnel in this environment can wedge; jax.devices() then blocks
    forever in C. Probe it in a subprocess with a timeout and force the CPU
    backend on failure, so the benchmark always produces its JSON line.
    Probing only happens when an accelerator platform is configured (a CPU
    run has nothing to probe), and the diagnostic goes to stderr — stdout
    stays exactly one JSON line.

    Returns whether the Pallas lowering is safe to use in THIS process: a
    wedged pallas compile cannot be caught in-process (it hangs, not
    raises), so the impl sweep must exclude pallas when the subprocess
    probe failed.
    """
    import subprocess
    import sys

    import jax

    # the environment's sitecustomize force-configures the platform list
    # (e.g. "axon,cpu") regardless of JAX_PLATFORMS in the env, so the env
    # var says nothing — read the live config (safe: no backend init)
    platform = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    if platform and not any(t in platform for t in ("tpu", "axon")):
        return True  # CPU run: pallas runs in interpret mode, cannot wedge
    probe_code = (
        "import jax, jax.numpy as jnp; jax.devices(); "
        "import sys; sys.path.insert(0, %r); "
        "from flox_tpu.pallas_kernels import segment_sum_pallas; "
        "out = segment_sum_pallas(jnp.ones((8, 128), jnp.float32), "
        "jnp.zeros(8, jnp.int32), 2); "
        "assert float(out[0, 0]) == 8.0"
    ) % os.path.dirname(os.path.abspath(__file__))
    proc = subprocess.Popen(
        [sys.executable, "-c", probe_code],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    healthy = False
    try:
        healthy = proc.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            # a child wedged in uninterruptible sleep may never reap; don't
            # let the guard itself hang — orphan it and move on
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
    if not healthy:
        # either the backend is wedged or the pallas lowering misbehaves in a
        # way an in-process try/except cannot catch; find out which
        basic = subprocess.Popen(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        backend_ok = False
        try:
            backend_ok = basic.wait(timeout=timeout_s) == 0
        except subprocess.TimeoutExpired:
            basic.kill()
            try:
                basic.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        import jax

        if backend_ok:
            print("flox-tpu bench: pallas probe failed; using the XLA GEMM path", file=sys.stderr, flush=True)
            from flox_tpu.options import OPTIONS

            OPTIONS["segment_sum_impl"] = "matmul"
        else:
            print("flox-tpu bench: accelerator unreachable; benchmarking on CPU", file=sys.stderr, flush=True)
            jax.config.update("jax_platforms", "cpu")
        # broken-pallas-on-accelerator is the unsafe case; the CPU fallback
        # runs pallas in interpret mode, which cannot wedge
        return not backend_ok
    return True


def main() -> None:
    pallas_safe = _ensure_responsive_backend()

    import jax

    from flox_tpu.kernels import generic_kernel

    # However execution ended up on CPU (explicit env, wedged-accelerator
    # fallback, or a host with no accelerator at all), bound the default
    # workload: a full-size ERA5 pass takes ~15 min on one host core and the
    # CPU number is only a liveness signal. Env vars still override.
    on_cpu = jax.default_backend() == "cpu"
    default_ntime = (24 * 365) if on_cpu else (24 * 365 * 3)
    default_nlat = 60 if on_cpu else 181
    nlat = int(os.environ.get("FLOX_TPU_BENCH_NLAT", default_nlat))
    nlon = int(os.environ.get("FLOX_TPU_BENCH_NLON", 360))
    ntime = int(os.environ.get("FLOX_TPU_BENCH_NTIME", default_ntime))
    reps = int(os.environ.get("FLOX_TPU_BENCH_REPS", 5))

    # month-of-year labels for 3 years of hourly stamps (12 groups)
    hours = np.arange(ntime, dtype=np.int64)
    day = hours // 24
    month = ((day % 365) // 30.44).astype(np.int32) % 12
    size = 12

    nbytes = nlat * nlon * ntime * 4

    # --- TPU/jax path: generate the workload directly on device ------------
    # Shipping ~7 GB through the axon tunnel takes longer than the entire
    # measurement and is not part of the metric; synthesize the same
    # distribution on device instead.
    import jax.numpy as jnp

    dev_data = jax.jit(
        lambda k: jax.random.normal(k, (nlat * nlon, ntime), jnp.float32)
    )(jax.random.PRNGKey(0))
    dev_data.block_until_ready()
    dev_codes = jax.device_put(month)

    # Timing must NOT trust block_until_ready: through the axon tunnel it
    # returns before execution finishes (observed: 2.3 GB "reduced" in
    # 0.03 ms). Instead time a jitted chain of K dependent iterations with a
    # host fetch of the (tiny) result, and difference against a 1-iteration
    # chain so the constant fetch/dispatch overhead cancels:
    #   t_iter = (t_K - t_1) / (K - 1)
    # The inter-iteration dependence rides the (tiny) codes array — a
    # data-sized `v + f(out)` temp would double the HBM footprint and OOM
    # the full workload — so per-iteration HBM traffic stays ~one pass over
    # the same data buffer. XLA cannot fold the zero (out may be NaN/inf)
    # nor CSE the iterations (each sees a distinct codes value).
    def chain(iters):
        @jax.jit
        def run(c, v):
            import jax.numpy as jnp

            out = generic_kernel("nanmean", c, v, size=size)
            for _ in range(iters - 1):
                # nan_to_num: an empty group's NaN mean must not reach the
                # int cast (NaN->int is implementation-defined garbage)
                c2 = c + jnp.nan_to_num(out.ravel()[:1] * 0.0).astype(c.dtype)
                out = generic_kernel("nanmean", c2, v, size=size)
            return out

        return run

    chain_k = max(2, int(os.environ.get("FLOX_TPU_BENCH_CHAIN", 8)))

    def best_time(fn):
        np.asarray(fn(dev_codes, dev_data))  # compile + warm
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            np.asarray(fn(dev_codes, dev_data))
            times.append(time.perf_counter() - t0)
        return min(times)

    def measure_impl():
        t_1 = best_time(chain(1))
        t_k = best_time(chain(chain_k))
        t = (t_k - t_1) / (chain_k - 1)
        # noise floor: fall back to the single-shot fetch time
        return t_1 if t <= 0 else t

    # On an accelerator, sweep the three segment-sum lowerings and take the
    # winner: the driver's round-end bench then doubles as the on-hardware
    # policy measurement (scatter vs MXU one-hot GEMM vs Pallas). A failing
    # lowering (e.g. a flaky remote compile) drops out instead of killing
    # the run. On CPU the sweep is pointless (auto == scatter there).
    import sys

    from flox_tpu.options import OPTIONS

    if on_cpu and not os.environ.get("FLOX_TPU_BENCH_FORCE_SWEEP"):
        from flox_tpu.kernels import _segment_sum_impl

        t_dev = measure_impl()
        # label with the impl the policy resolves to, not the policy string
        winner = _segment_sum_impl(
            jax.ShapeDtypeStruct((ntime, nlat * nlon), np.float32), size
        )
        sweep_gbps = {}
    else:
        from flox_tpu.kernels import _segment_sum_impl

        # the kernel sees the array with the reduce axis leading
        proxy = jax.ShapeDtypeStruct((ntime, nlat * nlon), np.float32)
        impls = ("scatter", "matmul") + (("pallas",) if pallas_safe else ())
        sweep: dict = {}
        for impl in impls:
            OPTIONS["segment_sum_impl"] = impl
            # explicit policies silently fall back to scatter when their
            # guards fail — measure (and label) what would actually run, or
            # the sweep reports a scatter time under another impl's name
            resolved = _segment_sum_impl(proxy, size)
            if resolved != impl:
                print(f"flox-tpu bench: impl {impl!r} resolves to {resolved!r} "
                      "here; skipping duplicate measurement", file=sys.stderr, flush=True)
                continue
            try:
                sweep[impl] = measure_impl()
            except Exception as exc:  # noqa: BLE001 — keep the bench alive
                print(f"flox-tpu bench: impl {impl!r} failed: {exc}",
                      file=sys.stderr, flush=True)
                sweep[impl] = None
            jax.clear_caches()
        ok = {k: v for k, v in sweep.items() if v}
        if not ok:
            raise RuntimeError(f"all segment-sum impls failed: {sweep}")
        winner = min(ok, key=ok.get)
        OPTIONS["segment_sum_impl"] = winner
        t_dev = ok[winner]
        sweep_gbps = {k: round(nbytes / v / 1e9, 2) for k, v in ok.items()}
    gbps = nbytes / t_dev / 1e9

    # --- host baseline: an independent numpy_groupies-equivalent -----------
    # numpy_groupies is not installed; its nanmean primitive is
    # bincount-with-weights (npg aggregate_numpy), reproduced verbatim here
    # so the baseline is NOT this repo's own engine (BASELINE.json names
    # single-host numpy_groupies as the reference point).
    def npg_equivalent_nanmean(codes, values, size):
        ncols = values.shape[0]
        flat_codes = (
            np.broadcast_to(codes, values.shape)
            + (np.arange(ncols, dtype=np.int32)[:, None] * size)
        ).reshape(-1)
        v = values.reshape(-1)
        nanmask = np.isnan(v)
        zeroed = np.where(nanmask, 0.0, v)
        sums = np.bincount(flat_codes, weights=zeroed, minlength=ncols * size)
        cnts = np.bincount(flat_codes[~nanmask], minlength=ncols * size)
        with np.errstate(invalid="ignore"):
            return (sums / cnts).reshape(ncols, size)

    # bincount throughput is size-invariant well before this point; a bounded
    # row subset (~512 MB) keeps the single-core baseline measurement (and
    # its flat-codes temporary) from dominating the benchmark's wall-clock.
    host_rows = min(nlat * nlon, max(1, int(512e6) // (ntime * 4)))
    rng = np.random.default_rng(0)
    host_data = rng.normal(size=(host_rows, ntime)).astype(np.float32)
    t0 = time.perf_counter()
    npg_equivalent_nanmean(month, host_data, size)
    t_host = time.perf_counter() - t0
    gbps_host = host_data.nbytes / t_host / 1e9

    backend = jax.default_backend()
    print(
        json.dumps(
            {
                "metric": "ERA5 groupby(time.month).mean() GB/s/chip",
                "value": round(gbps, 2),
                "unit": "GB/s",
                "vs_baseline": round(gbps / gbps_host, 2),
                "baseline": "single-host bincount nanmean (numpy_groupies equivalent)",
                "platform": backend,
                "segment_sum_impl": winner,
                "impl_sweep_gbps": sweep_gbps,
                "note": (
                    "CPU FALLBACK — accelerator unreachable; value is a liveness "
                    "signal, NOT a TPU measurement"
                )
                if backend == "cpu"
                else "measured on accelerator; winner of the impl sweep",
            }
        )
    )


if __name__ == "__main__":
    main()
