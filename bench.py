"""Headline benchmark: ERA5 hourly -> monthly-mean climatology on one chip.

Metric (BASELINE.json): ERA5 ``groupby('time.month').mean()`` GB/s/chip.
Baseline: the in-repo host numpy engine (``ufunc.at``/bincount — the same
primitive family as the reference's numpy_groupies engine) on the identical
workload. Prints ONE JSON line.

Scale knobs (env):
  FLOX_TPU_BENCH_NLAT / NLON / NTIME — workload shape (default 181x360x26304,
  ~6.8 GB float32: 3 years of hourly steps on a 1-degree grid).
  FLOX_TPU_BENCH_REPS — timed repetitions (default 5).
  FLOX_TPU_BENCH_CHAIN — iterations in the differenced timing chain
  (default 8, min 2; see the timing note in main()).
  FLOX_TPU_BENCH_FORCE_SWEEP — nonempty: run the scatter/matmul/pallas
  impl sweep even on CPU (testing aid; on accelerators it always runs).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))
TPU_LAST_PATH = os.path.join(_REPO, "BENCH_TPU_LAST.json")
HISTORY_PATH = os.path.join(_REPO, "BENCH_HISTORY", "bench_runs.jsonl")


def _load_last_onchip():
    """Last successful on-chip sweep, or None. The tunnel to the chip flaps;
    a capture that lands during an outage must still carry the most recent
    hardware evidence (explicitly timestamped, never passed off as fresh)."""
    try:
        with open(TPU_LAST_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _persist_onchip(record: dict) -> None:
    try:
        with open(TPU_LAST_PATH, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
    except OSError as exc:  # pragma: no cover - read-only checkout
        import sys

        print(f"flox-tpu bench: could not persist on-chip record: {exc}",
              file=sys.stderr, flush=True)


def _append_history(line: dict) -> None:
    try:
        os.makedirs(os.path.dirname(HISTORY_PATH), exist_ok=True)
        with open(HISTORY_PATH, "a") as f:
            f.write(json.dumps(line) + "\n")
    except OSError as exc:  # pragma: no cover
        import sys

        print(f"flox-tpu bench: could not append history: {exc}",
              file=sys.stderr, flush=True)


def _probe_once(code: str, timeout_s: float) -> bool:
    """Run ``code`` in a subprocess with a hard timeout (a wedged TPU
    runtime blocks forever in C and cannot be interrupted in-process)."""
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        return proc.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            # a child wedged in uninterruptible sleep may never reap; don't
            # let the guard itself hang — orphan it and move on
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        return False


def _ensure_responsive_backend(
    timeout_s: float = 90.0, attempts: int = 3, spacing_s: float = 75.0
) -> bool:
    """Fall back to CPU if the accelerator runtime hangs at device init.

    The TPU tunnel in this environment flaps; jax.devices() then blocks
    forever in C. Probe it in a subprocess with a timeout — and because an
    outage is often transient, retry with spaced backoff (default: 3
    attempts over ~6 min) before giving up on the round's hardware
    evidence. Diagnostics go to stderr — stdout stays one JSON line.

    Returns whether the Pallas lowering is safe to use in THIS process: a
    wedged pallas compile cannot be caught in-process (it hangs, not
    raises), so the impl sweep must exclude pallas when the subprocess
    probe failed.
    """
    import sys

    import jax

    # the environment's sitecustomize force-configures the platform list
    # (e.g. "axon,cpu") regardless of JAX_PLATFORMS in the env, so the env
    # var says nothing — read the live config (safe: no backend init)
    platform = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    if platform and not any(t in platform for t in ("tpu", "axon")):
        return True  # CPU run: pallas runs in interpret mode, cannot wedge
    pallas_code = (
        "import jax, jax.numpy as jnp; jax.devices(); "
        "import sys; sys.path.insert(0, %r); "
        "from flox_tpu.pallas_kernels import segment_sum_pallas; "
        "out = segment_sum_pallas(jnp.ones((8, 128), jnp.float32), "
        "jnp.zeros(8, jnp.int32), 2); "
        "assert float(out[0, 0]) == 8.0"
    ) % _REPO
    basic_code = "import jax; jax.devices()"

    backend_ok = False
    pallas_ok = False
    for attempt in range(attempts):
        if attempt:
            print(
                f"flox-tpu bench: accelerator probe retry {attempt + 1}/"
                f"{attempts} in {spacing_s:.0f}s", file=sys.stderr, flush=True,
            )
            time.sleep(spacing_s)
        if _probe_once(pallas_code, timeout_s):
            backend_ok = pallas_ok = True
            break
        if _probe_once(basic_code, timeout_s):
            # backend alive but the pallas probe failed — that could still
            # be a transient flap mid-compile, not a deterministic lowering
            # failure; re-probe pallas with the same spaced backoff as the
            # backend before excluding it from the round's persisted
            # hardware evidence (ADVICE r3: one unspaced retry loses pallas
            # to a flap that the next minute would have survived)
            backend_ok = True
            # the pallas probe failed SECONDS ago, so every re-probe is
            # spaced (sleep first, including the first), with at least two
            # tries even when the backend only recovered on the last outer
            # attempt
            for p_attempt in range(max(2, attempts - attempt)):
                print(
                    f"flox-tpu bench: pallas probe retry {p_attempt + 1} "
                    f"in {spacing_s:.0f}s", file=sys.stderr, flush=True,
                )
                time.sleep(spacing_s)
                pallas_ok = _probe_once(pallas_code, timeout_s)
                if pallas_ok:
                    break
            break
    if backend_ok and not pallas_ok:
        print("flox-tpu bench: pallas probe failed; using the XLA GEMM path",
              file=sys.stderr, flush=True)
        from flox_tpu.options import OPTIONS

        OPTIONS["segment_sum_impl"] = "matmul"
        # broken-pallas-on-accelerator is the unsafe case that cannot be
        # caught in-process
        return False
    if not backend_ok:
        print("flox-tpu bench: accelerator unreachable after "
              f"{attempts} spaced probes; benchmarking on CPU",
              file=sys.stderr, flush=True)
        jax.config.update("jax_platforms", "cpu")
        # the CPU fallback runs pallas in interpret mode, which cannot wedge
        return True
    return True


def main() -> None:
    pallas_safe = _ensure_responsive_backend()

    import jax

    from flox_tpu.kernels import generic_kernel

    # However execution ended up on CPU (explicit env, wedged-accelerator
    # fallback, or a host with no accelerator at all), bound the default
    # workload: a full-size ERA5 pass takes ~15 min on one host core and the
    # CPU number is only a liveness signal. Env vars still override.
    on_cpu = jax.default_backend() == "cpu"
    # 3 calendar years of hourly steps INCLUDING the 2016 leap day = 26304,
    # the headline shape (BASELINE.md: array (721, 1440, 26304))
    default_ntime = (24 * 365) if on_cpu else (24 * (365 * 3 + 1))
    default_nlat = 60 if on_cpu else 181
    nlat = int(os.environ.get("FLOX_TPU_BENCH_NLAT", default_nlat))
    nlon = int(os.environ.get("FLOX_TPU_BENCH_NLON", 360))
    ntime = int(os.environ.get("FLOX_TPU_BENCH_NTIME", default_ntime))
    reps = int(os.environ.get("FLOX_TPU_BENCH_REPS", 5))

    # month-of-year labels for 3 years of hourly stamps (12 groups)
    hours = np.arange(ntime, dtype=np.int64)
    day = hours // 24
    month = ((day % 365) // 30.44).astype(np.int32) % 12
    size = 12

    nbytes = nlat * nlon * ntime * 4

    # --- TPU/jax path: generate the workload directly on device ------------
    # Shipping ~7 GB through the axon tunnel takes longer than the entire
    # measurement and is not part of the metric; synthesize the same
    # distribution on device instead.
    import jax.numpy as jnp

    dev_data = jax.jit(
        lambda k: jax.random.normal(k, (nlat * nlon, ntime), jnp.float32)
    )(jax.random.PRNGKey(0))
    dev_data.block_until_ready()
    dev_codes = jax.device_put(month)

    # Timing must NOT trust block_until_ready: through the axon tunnel it
    # returns before execution finishes (observed: 2.3 GB "reduced" in
    # 0.03 ms). Instead time a jitted chain of K dependent iterations with a
    # host fetch of the (tiny) result, and difference against a 1-iteration
    # chain so the constant fetch/dispatch overhead cancels:
    #   t_iter = (t_K - t_1) / (K - 1)
    # The inter-iteration dependence rides the (tiny) codes array — a
    # data-sized `v + f(out)` temp would double the HBM footprint and OOM
    # the full workload — so per-iteration HBM traffic stays ~one pass over
    # the same data buffer. XLA cannot fold the zero (out may be NaN/inf)
    # nor CSE the iterations (each sees a distinct codes value).
    def chain(iters, func, **kw):
        @jax.jit
        def run(c, v):
            out = generic_kernel(func, c, v, size=size, **kw)
            for _ in range(iters - 1):
                # nan_to_num: an empty group's NaN mean must not reach the
                # int cast (NaN->int is implementation-defined garbage)
                c2 = c + jnp.nan_to_num(out.ravel()[:1] * 0.0).astype(c.dtype)
                out = generic_kernel(func, c2, v, size=size, **kw)
            return out

        return run

    chain_k = max(2, int(os.environ.get("FLOX_TPU_BENCH_CHAIN", 8)))

    def best_time(fn, data):
        np.asarray(fn(dev_codes, data))  # compile + warm
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            np.asarray(fn(dev_codes, data))
            times.append(time.perf_counter() - t0)
        return min(times)

    def measure_impl(func="nanmean", data=None, **kw):
        data = dev_data if data is None else data
        t_1 = best_time(chain(1, func, **kw), data)
        t_k = best_time(chain(chain_k, func, **kw), data)
        t = (t_k - t_1) / (chain_k - 1)
        # noise floor: fall back to the single-shot fetch time
        return t_1 if t <= 0 else t

    # On an accelerator, sweep the three segment-sum lowerings and take the
    # winner: the driver's round-end bench then doubles as the on-hardware
    # policy measurement (scatter vs MXU one-hot GEMM vs Pallas). A failing
    # lowering (e.g. a flaky remote compile) drops out instead of killing
    # the run. On CPU the sweep is pointless (auto == scatter there).
    import sys

    from flox_tpu.options import OPTIONS

    if on_cpu and not os.environ.get("FLOX_TPU_BENCH_FORCE_SWEEP"):
        from flox_tpu.kernels import _segment_sum_impl

        t_dev = measure_impl()
        # label with the impl the policy resolves to, not the policy string
        winner = _segment_sum_impl(
            jax.ShapeDtypeStruct((ntime, nlat * nlon), np.float32), size
        )
        sweep_gbps = {}
    else:
        from flox_tpu.kernels import _segment_sum_impl

        # the kernel sees the array with the reduce axis leading
        proxy = jax.ShapeDtypeStruct((ntime, nlat * nlon), np.float32)
        impls = ("scatter", "matmul") + (("pallas",) if pallas_safe else ())
        sweep: dict = {}
        for impl in impls:
            OPTIONS["segment_sum_impl"] = impl
            # explicit policies silently fall back to scatter when their
            # guards fail — measure (and label) what would actually run, or
            # the sweep reports a scatter time under another impl's name
            resolved = _segment_sum_impl(proxy, size)
            if resolved != impl:
                print(f"flox-tpu bench: impl {impl!r} resolves to {resolved!r} "
                      "here; skipping duplicate measurement", file=sys.stderr, flush=True)
                continue
            try:
                sweep[impl] = measure_impl()
            except Exception as exc:  # noqa: BLE001 — keep the bench alive
                print(f"flox-tpu bench: impl {impl!r} failed: {exc}",
                      file=sys.stderr, flush=True)
                sweep[impl] = None
            jax.clear_caches()
        ok = {k: v for k, v in sweep.items() if v}
        if not ok:
            raise RuntimeError(f"all segment-sum impls failed: {sweep}")
        winner = min(ok, key=ok.get)
        OPTIONS["segment_sum_impl"] = winner
        t_dev = ok[winner]
        sweep_gbps = {k: round(nbytes / v / 1e9, 2) for k, v in ok.items()}
    gbps = nbytes / t_dev / 1e9

    # --- host baseline: an independent numpy_groupies-equivalent -----------
    # numpy_groupies is not installed; its nanmean primitive is
    # bincount-with-weights (npg aggregate_numpy), reproduced verbatim here
    # so the baseline is NOT this repo's own engine (BASELINE.json names
    # single-host numpy_groupies as the reference point).
    def npg_equivalent_nanmean(codes, values, size):
        ncols = values.shape[0]
        flat_codes = (
            np.broadcast_to(codes, values.shape)
            + (np.arange(ncols, dtype=np.int32)[:, None] * size)
        ).reshape(-1)
        v = values.reshape(-1)
        nanmask = np.isnan(v)
        zeroed = np.where(nanmask, 0.0, v)
        sums = np.bincount(flat_codes, weights=zeroed, minlength=ncols * size)
        cnts = np.bincount(flat_codes[~nanmask], minlength=ncols * size)
        with np.errstate(invalid="ignore"):
            return (sums / cnts).reshape(ncols, size)

    # bincount throughput is size-invariant well before this point; a bounded
    # row subset (~512 MB) keeps the single-core baseline measurement (and
    # its flat-codes temporary) from dominating the benchmark's wall-clock.
    host_rows = min(nlat * nlon, max(1, int(512e6) // (ntime * 4)))
    rng = np.random.default_rng(0)
    host_data = rng.normal(size=(host_rows, ntime)).astype(np.float32)
    t0 = time.perf_counter()
    npg_equivalent_nanmean(month, host_data, size)
    t_host = time.perf_counter() - t0
    gbps_host = host_data.nbytes / t_host / 1e9

    # --- order statistics on chip (VERDICT r2 #3): grouped quantile -------
    # The two-key lax.sort path is the open perf question; record its
    # throughput next to the mean's so the gap is a measured artifact, not
    # a guess. Bounded rows: the sort materializes ~3 data-sized arrays
    # (codes/data/iota), so the full ~7 GB workload would not fit HBM.
    backend = jax.default_backend()
    on_accel = backend != "cpu"
    quantile_gbps = None
    if on_accel or os.environ.get("FLOX_TPU_BENCH_FORCE_SWEEP"):
        # sweep BOTH order-statistics lowerings (VERDICT r3 #3): the two-key
        # lax.sort path vs the sort-free radix-select (nbits segment-sum
        # counting passes on the MXU). The recorded dict is the measurement
        # that decides the "auto" policy.
        import flox_tpu

        q_rows = min(nlat * nlon, max(1, int(1.0e9) // (ntime * 4)))
        quantile_gbps = {}
        for qimpl in ("sort", "select"):
            try:
                with flox_tpu.set_options(quantile_impl=qimpl):
                    tq = measure_impl("nanquantile", dev_data[:q_rows], q=0.9)
                quantile_gbps[qimpl] = round(q_rows * ntime * 4 / tq / 1e9, 2)
            except Exception as exc:  # noqa: BLE001 — keep the headline alive
                print(f"flox-tpu bench: quantile[{qimpl}] failed: {exc}",
                      file=sys.stderr, flush=True)
                quantile_gbps[qimpl] = None
            jax.clear_caches()
    # --- streaming pipeline: prefetched staging vs synchronous inline ----
    # (flox_tpu/pipeline.py) measured with a simulated-latency loader (a
    # ~zarr/S3 range read) so the overlap win is visible on any host; GB/s
    # against ONE logical read of the streamed bytes
    import flox_tpu
    from flox_tpu.streaming import streaming_groupby_reduce

    stream_lat_s = 0.005
    s_data = host_data[: min(host_rows, 256)]
    s_blen = max(1, ntime // 16)

    def _stream_loader(s, e):
        time.sleep(stream_lat_s)
        return s_data[:, s:e]

    def _stream_time(depth):
        with flox_tpu.set_options(stream_prefetch=depth):
            t0 = time.perf_counter()
            res = streaming_groupby_reduce(
                _stream_loader, month, func="nanmean", batch_len=s_blen
            )[0]
            np.asarray(res)  # streamed reduce is async — sync before stopping
            return time.perf_counter() - t0

    _stream_time(0)  # warm both modes (compile + thread-pool first-spin)
    _stream_time(2)
    t_sync = min(_stream_time(0) for _ in range(2))
    t_pre = min(_stream_time(2) for _ in range(2))
    streaming = {
        "simio_latency_ms": stream_lat_s * 1e3,
        "gbps_sync": round(s_data.nbytes / t_sync / 1e9, 3),
        "gbps_prefetch": round(s_data.nbytes / t_pre / 1e9, 3),
        "prefetch_speedup": round(t_sync / t_pre, 2),
    }

    # --- multi-statistic fusion: one pass vs N sequential passes ----------
    # (flox_tpu/fusion.py) the climatology family set {mean, var, min, max}
    # through groupby_aggregate_many (one program, bytes staged once) vs
    # four sequential groupby_reduce passes. GB/s is against ONE logical
    # read of the bytes for BOTH, so the sequential number directly shows
    # the bytes-touched penalty; the measurements seed the "fused"
    # autotune family that arbitrates the dispatch.
    fused_info = None
    try:
        f_funcs = ("mean", "var", "min", "max")
        f_rows = min(nlat * nlon, max(1, int(256e6) // (ntime * 4)))
        f_data = dev_data[:f_rows]
        f_bytes = f_rows * ntime * 4
        f_reps = max(2, reps // 2)

        def _t_fused():
            t0 = time.perf_counter()
            outs, _ = flox_tpu.groupby_aggregate_many(f_data, month, funcs=f_funcs)
            for v in outs.values():
                np.asarray(v)
            return time.perf_counter() - t0

        def _t_seq():
            t0 = time.perf_counter()
            for f in f_funcs:
                np.asarray(flox_tpu.groupby_reduce(f_data, month, func=f)[0])
            return time.perf_counter() - t0

        _t_fused()  # compile + warm both paths outside the timed reps
        _t_seq()
        t_fused = min(_t_fused() for _ in range(f_reps))
        t_seq = min(_t_seq() for _ in range(f_reps))
        fused_info = {
            "funcs": list(f_funcs),
            # the band the sweep actually measured (f_rows may be far
            # below the headline workload) — autotune records key on it
            "nelems": f_rows * ntime,
            "fused_sweep_gbps": {
                "fused": round(f_bytes / t_fused / 1e9, 3),
                "sequential": round(f_bytes / t_seq / 1e9, 3),
            },
            "speedup": round(t_seq / t_fused, 2),
        }
    except Exception as exc:  # noqa: BLE001 — keep the headline alive
        print(f"flox-tpu bench: fused sweep failed: {exc}",
              file=sys.stderr, flush=True)

    # --- high-cardinality: dense vs the sort (present-groups) engine ------
    # (kernels.py sort section) a million-label universe with sparse
    # presence — the user-ID / geohash / station-ID regime. GB/s against
    # ONE logical read of the data for BOTH engines, so the dense number
    # directly shows the ngroups-accumulator penalty. The pair seeds the
    # "highcard" autotune family, and the coarse universe scan records the
    # dense-vs-sort crossover band (docs/engines.md).
    highcard_info = None
    try:
        hc_size = 1 << 20
        hc_n = 1 << 16
        hc_present = 1 << 12  # 0.4% of the universe present
        rng_hc = np.random.default_rng(11)
        hc_ids = rng_hc.choice(hc_size, hc_present, replace=False)
        hc_codes = hc_ids[rng_hc.integers(0, hc_present, hc_n)]
        hc_vals = jax.device_put(
            rng_hc.normal(size=hc_n).astype(np.float32)
        )
        hc_reps = max(2, reps // 2)

        def _t_hc(engine, universe):
            t0 = time.perf_counter()
            np.asarray(flox_tpu.groupby_reduce(
                hc_vals, hc_codes % universe, func="nanmean",
                expected_groups=np.arange(universe), engine=engine,
            )[0])
            return time.perf_counter() - t0

        _t_hc("jax", hc_size)  # compile + warm both engines
        _t_hc("sort", hc_size)
        t_hc_dense = min(_t_hc("jax", hc_size) for _ in range(hc_reps))
        t_hc_sort = min(_t_hc("sort", hc_size) for _ in range(hc_reps))
        # coarse crossover scan: the smallest universe (same data, labels
        # folded down) where the sort engine wins — the band boundary the
        # autotuner refines at runtime
        crossover = None
        for logu in range(13, 21):
            u = 1 << logu
            _t_hc("jax", u), _t_hc("sort", u)
            td = min(_t_hc("jax", u) for _ in range(2))
            ts = min(_t_hc("sort", u) for _ in range(2))
            if ts < td:
                crossover = u
                break
        highcard_info = {
            "ngroups": hc_size,
            "nelems": hc_n,
            "present": hc_present,
            "dense_gbps": round(hc_vals.nbytes / t_hc_dense / 1e9, 3),
            "sort_gbps": round(hc_vals.nbytes / t_hc_sort / 1e9, 3),
            "speedup": round(t_hc_dense / t_hc_sort, 2),
            "crossover_ngroups": crossover,
        }
    except Exception as exc:  # noqa: BLE001 — keep the headline alive
        print(f"flox-tpu bench: highcard sweep failed: {exc}",
              file=sys.stderr, flush=True)

    # --- resident dataset registry: inline vs registry-hit serving --------
    # (flox_tpu/serve/registry.py) the factorize-once fast path measured at
    # three payload sizes over the serve-loop request path: each rep times
    # json.loads of the request line + dispatch, exactly what a replica
    # pays per protocol line. An inline request parses its full payload
    # from JSON, digests it, factorizes, and stages it H2D; a registry hit
    # parses a ~40-byte line and reuses the pinned, prefactorized entry
    # (its stored fingerprint IS the program key — zero hashing). The line
    # text is encoded OUTSIDE the timing (client cost, not replica cost).
    # batch_window=0 so neither path pays the micro-batch wait, and
    # sequential awaits mean no coalescing: every rep is a real dispatch.
    # p50 (not min): the win is a per-request overhead, so the central
    # tendency is the honest number.
    registry_info = None
    try:
        import asyncio

        from flox_tpu.serve import registry as _dsregistry
        from flox_tpu.serve.dispatcher import AggregationRequest, Dispatcher

        r_reps = max(9, reps)
        r_sizes = (1 << 14, 1 << 16, 1 << 18)
        rng_r = np.random.default_rng(7)
        r_fields = ("array", "by", "func", "dataset")

        async def _registry_sweep() -> dict:
            rows: dict = {}
            d = Dispatcher(batch_window=0.0)
            try:
                for n_r in r_sizes:
                    vals = rng_r.normal(size=n_r).astype(np.float32)
                    labels = rng_r.integers(0, 12, size=n_r).astype(np.int32)
                    name = f"bench-{n_r}"
                    _dsregistry.put(name, array=vals, by=labels)
                    inline_line = json.dumps({
                        "array": vals.tolist(), "by": labels.tolist(),
                        "func": "mean",
                    })
                    hit_line = json.dumps({"func": "mean", "dataset": name})

                    async def _once(line: str) -> None:
                        msg = json.loads(line)
                        await d.submit(AggregationRequest(
                            **{k: msg.get(k) for k in r_fields}))

                    async def _p50(line: str) -> float:
                        await _once(line)  # compile + warm
                        times = []
                        for _ in range(r_reps):
                            t0 = time.perf_counter()
                            await _once(line)
                            times.append(time.perf_counter() - t0)
                        return float(np.median(times))

                    t_inline = await _p50(inline_line)
                    t_hit = await _p50(hit_line)
                    rows[str(n_r)] = {
                        "p50_inline_ms": round(t_inline * 1e3, 3),
                        "p50_hit_ms": round(t_hit * 1e3, 3),
                        "inline_gbps": round(vals.nbytes / t_inline / 1e9, 3),
                        "hit_gbps": round(vals.nbytes / t_hit / 1e9, 3),
                        "speedup": round(t_inline / t_hit, 2),
                    }
                    _dsregistry.delete(name)
            finally:
                await d.close()
            return rows

        registry_info = {
            "platform": backend,
            "reps": r_reps,
            "timed_path": "json.loads(request line) + dispatch (the serve "
                          "loop's per-line cost); line encode is client-side",
            "sizes": asyncio.run(_registry_sweep()),
        }
    except Exception as exc:  # noqa: BLE001 — keep the headline alive
        print(f"flox-tpu bench: registry sweep failed: {exc}",
              file=sys.stderr, flush=True)

    # --- durable incremental aggregation store (ISSUE 18) -----------------
    # (flox_tpu/store.py) two numbers: append throughput — what one
    # exactly-once durable ingest costs (journal fsync + checksummed
    # segment write per slab) — and the read-path win the store exists
    # for: query() merges the persisted O(ngroups) present-groups carry
    # instead of re-reducing raw history, timed against recomputing the
    # full concatenated history inline at three history lengths. The
    # recompute cost grows with history; the store query does not. The
    # analytic store-vs-recompute verdict (costmodel "store_query" family)
    # rides along so a committed artifact shows prediction next to
    # measurement. History lengths shrink with FLOX_TPU_BENCH_REPS<=2 so
    # the CI smoke round stays cheap.
    store_info = None
    try:
        import shutil
        import tempfile

        from flox_tpu.store import IncrementalAggregationStore

        s_funcs = ("sum", "count", "mean", "var")
        s_ngroups = 64
        s_n = 1 << 13 if reps <= 2 else 1 << 15
        s_gens = (4, 16) if reps <= 2 else (8, 32, 128)
        s_reps = max(3, reps)
        rng_s = np.random.default_rng(11)
        sroot = tempfile.mkdtemp(prefix="flox-bench-store-")
        try:
            s = IncrementalAggregationStore.create(
                os.path.join(sroot, "bench"), funcs=s_funcs, size=s_ngroups
            )
            slab_list: list = []
            append_times: list = []
            lengths: dict = {}
            for target in s_gens:
                while len(slab_list) < target:
                    codes = rng_s.integers(0, s_ngroups, size=s_n)
                    vals = rng_s.normal(size=s_n)
                    slab_list.append((codes, vals))
                    t0 = time.perf_counter()
                    s.append(codes, vals)
                    append_times.append(time.perf_counter() - t0)
                s.query()  # warm (first query after appends builds the carry)
                tq = []
                for _ in range(s_reps):
                    t0 = time.perf_counter()
                    s.query()
                    tq.append(time.perf_counter() - t0)
                t_store = float(np.median(tq))
                all_codes = np.concatenate([c for c, _ in slab_list])
                all_vals = np.concatenate([v for _, v in slab_list])
                tr = []
                for _ in range(s_reps):
                    t0 = time.perf_counter()
                    res, _ = flox_tpu.groupby_aggregate_many(
                        all_vals, all_codes, funcs=s_funcs,
                        expected_groups=np.arange(s_ngroups),
                    )
                    for v in res.values():
                        np.asarray(v)
                    tr.append(time.perf_counter() - t0)
                t_rec = float(np.median(tr))
                lengths[str(target)] = {
                    "history_mb": round(all_vals.nbytes / 1e6, 2),
                    "p50_query_ms": round(t_store * 1e3, 3),
                    "p50_recompute_ms": round(t_rec * 1e3, 3),
                    "speedup": round(t_rec / t_store, 2),
                }
            append_p50 = float(np.median(append_times))
            store_info = {
                "platform": backend,
                "reps": s_reps,
                "slab_elems": s_n,
                "ngroups": s_ngroups,
                "funcs": list(s_funcs),
                "p50_append_ms": round(append_p50 * 1e3, 3),
                "append_mbps": round(
                    (s_n * 8) / append_p50 / 1e6, 1
                ),
                "timed_path": "query() = persisted-carry merge + finalize; "
                              "recompute = groupby_aggregate_many over the "
                              "full concatenated history",
                "lengths": lengths,
            }
            try:
                from flox_tpu import costmodel as _cm

                with flox_tpu.set_options(costmodel=True, telemetry=True):
                    store_info["analytic_verdict"] = _cm.analytic_prior(
                        "store_query", "recompute", ("store", "recompute"),
                        nelems=len(slab_list) * s_n, ngroups=s_ngroups,
                        dtype="float64",
                    )
            except Exception:  # noqa: BLE001 — verdict is decoration
                pass
        finally:
            shutil.rmtree(sroot, ignore_errors=True)
    except Exception as exc:  # noqa: BLE001 — keep the headline alive
        print(f"flox-tpu bench: store sweep failed: {exc}",
              file=sys.stderr, flush=True)

    # --- telemetry profile of the headline reduction (ISSUE 4) ------------
    # one instrumented pass, OUTSIDE the timed reps so the numbers above
    # stay clean: compile counts + span-phase breakdown make this round
    # diagnosable after the fact — above all the CPU-fallback case, where
    # a low GB/s alone cannot distinguish a retrace storm from a staging
    # bottleneck from plain host-core arithmetic
    from flox_tpu import cache as _flox_cache, telemetry as _telemetry

    try:
        _flox_cache.clear_all()
        jax.clear_caches()
        # the full user-facing path (factorize -> dispatch -> combine ->
        # finalize), not the bare chain kernel: phase spans only exist there
        telemetry_profile = _telemetry.profile_call(
            lambda: np.asarray(
                flox_tpu.groupby_reduce(dev_data, month, func="nanmean")[0]
            )
        )
    except Exception as exc:  # noqa: BLE001 — diagnostics must not kill the bench
        print(f"flox-tpu bench: telemetry profile failed: {exc}",
              file=sys.stderr, flush=True)
        telemetry_profile = None

    # --- analytical cost model (ISSUE 14) ---------------------------------
    # re-run the headline call with the cards plane on: the round's JSON
    # carries each program's analytical flops/bytes + roofline predicted_ms
    # next to the measured GB/s, and the drift sentinel verdict — the
    # "silently got slower after a JAX upgrade" regression detector riding
    # every committed bench artifact
    try:
        from flox_tpu import costmodel as _costmodel

        with flox_tpu.set_options(telemetry=True, costmodel=True):
            np.asarray(flox_tpu.groupby_reduce(dev_data, month, func="nanmean")[0])
            drift = _costmodel.drift_report()
            costmodel_record = {
                # keyed by digest — the registry's identity: one label can
                # hold several cards (one per input signature), and a
                # committed artifact must not let them overwrite each other
                "cards": {
                    digest: {
                        "label": card["label"],
                        "flops": card["flops"],
                        "bytes_accessed": card["bytes_accessed"],
                        "predicted_ms": card["predicted_ms"],
                        "analysis": card["analysis"],
                    }
                    for digest, card in _costmodel.cards().items()
                },
                "platform": _costmodel.platform_name(),
                "drift_flagged": drift["flagged"],
                "drift_threshold": drift["threshold"],
            }
    except Exception as exc:  # noqa: BLE001 — diagnostics must not kill the bench
        print(f"flox-tpu bench: costmodel failed: {exc}",
              file=sys.stderr, flush=True)
        costmodel_record = None

    # --- autotune store feed + regression sentinel (ISSUE 6) --------------
    # the round's sweep results ARE the measurements the autotuner's `auto`
    # dispatch wants: record them under the workload's bands (source=bench
    # outranks nothing — EWMA-merged like any observation) and persist when
    # a cache path is configured. The sentinel then diffs this round's
    # per-family GB/s against the LAST history round (same platform) —
    # computed BEFORE this round is appended, so it compares rounds, not
    # the round against itself.
    from flox_tpu import autotune

    try:
        nelems_bench = nlat * nlon * ntime
        for impl, impl_gbps in sweep_gbps.items():
            autotune.record("segment_sum", impl, impl_gbps, dtype="float32",
                            ngroups=size, nelems=nelems_bench, source="bench")
        for qimpl, q_gbps in (quantile_gbps or {}).items():
            if q_gbps:
                autotune.record("quantile", qimpl, q_gbps, dtype="float32",
                                ngroups=size, nelems=nelems_bench, source="bench")
        # the fused sweep seeds the fused-vs-sequential dispatch family —
        # under the band it MEASURED (its bounded row subset), not the
        # headline workload's
        for cand, f_gbps in ((fused_info or {}).get("fused_sweep_gbps") or {}).items():
            if f_gbps:
                autotune.record(
                    "fused", cand, f_gbps, dtype="float32", ngroups=size,
                    nelems=(fused_info or {}).get("nelems", nelems_bench),
                    source="bench",
                )
        # the highcard sweep seeds the dense-vs-sort routing family, under
        # the universe/element bands it measured
        if highcard_info:
            for cand in ("dense", "sort"):
                hc_gbps = highcard_info.get(f"{cand}_gbps")
                if hc_gbps:
                    autotune.record(
                        "highcard", cand, hc_gbps, dtype="float32",
                        ngroups=highcard_info["ngroups"],
                        nelems=highcard_info["nelems"], source="bench",
                    )
        autotune.save()  # no-op without a configured autotune_cache_path
        families = {"headline": gbps}
        families.update({f"segment_sum[{k}]": v for k, v in sweep_gbps.items()})
        families.update(
            {f"quantile[{k}]": v for k, v in (quantile_gbps or {}).items() if v}
        )
        families["streaming[sync]"] = streaming["gbps_sync"]
        families["streaming[prefetch]"] = streaming["gbps_prefetch"]
        if highcard_info:
            families["highcard[dense]"] = highcard_info["dense_gbps"]
            families["highcard[sort]"] = highcard_info["sort_gbps"]
        families.update(
            {f"fused[{k}]": v
             for k, v in ((fused_info or {}).get("fused_sweep_gbps") or {}).items()
             if v}
        )
        regressions = autotune.regression_sentinel(
            families, history_path=HISTORY_PATH, platform=backend,
            workload={"nlat": nlat, "nlon": nlon, "ntime": ntime,
                      "nbytes": nbytes, "ngroups": size},
        )
        autotune_record = autotune.decision_record()
    except Exception as exc:  # noqa: BLE001 — diagnostics must not kill the bench
        print(f"flox-tpu bench: autotune/sentinel failed: {exc}",
              file=sys.stderr, flush=True)
        regressions = None
        autotune_record = None

    # one shared field set: the persisted hardware record and the stdout
    # line must never drift apart about what was measured
    core = {
        "metric": "ERA5 groupby(time.month).mean() GB/s/chip",
        "value": round(gbps, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbps / gbps_host, 2),
        "baseline": "single-host bincount nanmean (numpy_groupies equivalent)",
        "platform": backend,
        "segment_sum_impl": winner,
        "impl_sweep_gbps": sweep_gbps,
        "quantile_gbps": quantile_gbps,
        "streaming": streaming,
        "fused": fused_info,
        "highcard": highcard_info,
        "registry": registry_info,
        "store": store_info,
        "telemetry": telemetry_profile,
        "costmodel": costmodel_record,
        "autotune": autotune_record,
        "regressions": regressions,
    }
    if on_accel:
        # the round's hardware evidence: persist it so a later capture that
        # lands during a tunnel outage still carries a timestamped record
        _persist_onchip(
            {
                "timestamp_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                **core,
                "workload": {"nlat": nlat, "nlon": nlon, "ntime": ntime,
                             "nbytes": nbytes, "ngroups": size},
            }
        )
    line = {
        **core,
        "note": (
            "CPU FALLBACK — accelerator unreachable; value is a liveness "
            "signal, NOT a TPU measurement (see last_onchip for the most "
            "recent hardware sweep). impl_sweep_gbps/quantile_gbps are "
            "skipped by design on CPU (auto==scatter here; force with "
            "FLOX_TPU_BENCH_FORCE_SWEEP=1) — the per-family CPU record "
            "lives in BENCH_HISTORY/r{N}_cpu.jsonl (benchmarks.py, "
            "median-of-3 sweeps)"
        )
        if not on_accel
        else "measured on accelerator; winner of the impl sweep",
    }
    if not on_accel:
        last = _load_last_onchip()
        if last is not None:
            line["last_onchip"] = last
    _append_history({
        "wall_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        **line,
        # the sentinel matches rounds by platform AND workload: a bounded
        # smoke round must never be compared against a full-size one
        "workload": {"nlat": nlat, "nlon": nlon, "ntime": ntime,
                     "nbytes": nbytes, "ngroups": size},
    })
    print(json.dumps(line))


if __name__ == "__main__":
    main()
