"""Accuracy certification at ERA5 scale (VERDICT r2 #2, r3 #2).

Measures — does not argue — the error of every user-reachable reduction
path against a float64 host oracle on the headline workload family
(hourly -> monthly climatology: 26304 steps of ERA5-like temperatures,
12 month groups). Two metrics per path:

* ``max_ulp``  — worst output's distance, in float32 ULPs, from the
  f32-rounding of the exact f64 result (0 = correctly rounded);
* ``max_rel``  — worst relative error vs the f64 oracle.

Paths certified: the three segment-sum lowerings (XLA scatter, MXU
one-hot GEMM, Pallas) with the Pallas kernel in all three accumulation
disciplines (plain / kahan / dd), plus the user-facing fused nanmean and
nanvar through ``generic_kernel`` exactly as ``groupby_reduce`` runs
them.

On CPU the Pallas kernels run in interpret mode, which reproduces the
tiled accumulation structure but not Mosaic's exact MXU reduction order;
the on-chip run of this same script (driven by tools/onchip_capture.py,
persisted as ACCURACY_TPU_LAST.json) is the hardware certificate. The
reduction length is always the full 26304 steps — accumulation error
grows with N, not with the number of cells — while the cell count is
bounded off-chip to keep interpret mode tractable.

Usage:
    python bench_accuracy.py            # markdown table (for docs/engines.md)
    python bench_accuracy.py --json     # one JSON line

Env: FLOX_ACC_CELLS / FLOX_ACC_NTIME / FLOX_ACC_SEED override the shape.

Reference analogue: the reference certifies against numpy_groupies on
f64 hosts (tests/test_core.py assert_equal tolerances); on TPUs f64
hardware does not exist, so the certificate must be measured per path.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def _monotonic_key_f32(x: np.ndarray) -> np.ndarray:
    """Map f32 bit patterns to int64 keys whose difference counts the
    representable floats between two values (the standard sign-magnitude
    to two's-complement trick)."""
    u = np.ascontiguousarray(x, dtype=np.float32).view(np.uint32).astype(np.int64)
    return np.where(u < 0x80000000, u + 0x80000000, 0x100000000 - u)


def ulp_dist_f32(got: np.ndarray, want_f64: np.ndarray) -> np.ndarray:
    """ULP distance between ``got`` (f32) and the f32-rounding of the f64
    oracle. NaN/inf lanes are excluded by the caller."""
    return np.abs(
        _monotonic_key_f32(np.asarray(got, np.float32))
        - _monotonic_key_f32(want_f64.astype(np.float32))
    )


def _measure(got, want_f64):
    got64 = np.asarray(got, np.float64)
    finite = np.isfinite(want_f64) & (want_f64 != 0)
    rel = np.abs(got64 - want_f64)[finite] / np.abs(want_f64)[finite]
    return {
        "max_ulp": int(ulp_dist_f32(got, want_f64)[finite].max()),
        "max_rel": float(rel.max()),
    }


def run(cells: int, ntime: int, seed: int) -> dict:
    import jax

    from flox_tpu import set_options
    from flox_tpu.kernels import generic_kernel
    from flox_tpu.pallas_kernels import segment_sum_pallas

    on_accel = jax.default_backend() != "cpu"

    # month-of-year labels for hourly stamps — the headline workload's
    # grouping (12 groups, ~2192 members each at 3 years)
    day = np.arange(ntime, dtype=np.int64) // 24
    codes = (((day % 365) // 30.44).astype(np.int32)) % 12
    size = 12

    # ERA5-like 2m temperature in Kelvin: a large common offset is the
    # adversarial case for f32 accumulation (relative ULP of the running
    # sum >> ULP of the data)
    rng = np.random.default_rng(seed)
    data = (280.0 + 10.0 * rng.standard_normal((cells, ntime))).astype(np.float32)

    # f64 oracles on host
    want_sum = np.stack(
        [data[:, codes == g].astype(np.float64).sum(axis=1) for g in range(size)],
        axis=1,
    )
    want_mean = np.stack(
        [data[:, codes == g].astype(np.float64).mean(axis=1) for g in range(size)],
        axis=1,
    )
    want_var = np.stack(
        [data[:, codes == g].astype(np.float64).var(axis=1) for g in range(size)],
        axis=1,
    )

    dev = jax.device_put(data)
    dev_codes = jax.device_put(codes)

    table: dict[str, dict] = {}

    # --- segment-sum lowerings through the real dispatch ------------------
    for impl in ("scatter", "matmul"):
        with set_options(segment_sum_impl=impl):
            got = np.asarray(generic_kernel("sum", dev_codes, dev, size=size))
        table[f"sum/{impl}"] = _measure(got, want_sum)

    # pallas × accumulation discipline (kernel entry point: the dispatch
    # would pick one accum from options; the certificate needs all three)
    pdata = np.moveaxis(data, -1, 0)  # (N, K) as the kernel consumes it
    for accum in ("plain", "kahan", "dd"):
        got = np.asarray(
            segment_sum_pallas(
                pdata, codes, size, interpret=not on_accel, accum=accum
            )
        ).T
        table[f"sum/pallas-{accum}"] = _measure(got, want_sum)

    # --- user-facing fused paths exactly as groupby_reduce runs them ------
    got = np.asarray(generic_kernel("nanmean", dev_codes, dev, size=size))
    table["nanmean/auto"] = _measure(got, want_mean)
    got = np.asarray(generic_kernel("nanvar", dev_codes, dev, size=size))
    table["nanvar/auto"] = _measure(got, want_var)

    import time

    return {
        "timestamp_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "platform": jax.default_backend(),
        "pallas_mode": "mosaic" if on_accel else "interpret",
        "workload": {
            "cells": cells, "ntime": ntime, "ngroups": size,
            "distribution": "280 + 10*N(0,1) Kelvin f32", "seed": seed,
        },
        "table": table,
    }


def to_markdown(rec: dict) -> str:
    w = rec["workload"]
    lines = [
        f"Workload: {w['cells']} cells x {w['ntime']} hourly steps, "
        f"{w['ngroups']} month groups, {w['distribution']}; "
        f"platform={rec['platform']} (pallas: {rec['pallas_mode']}).",
        "",
        "| path | max ULP (f32) | max rel error |",
        "|---|---|---|",
    ]
    for path, m in rec["table"].items():
        lines.append(f"| {path} | {m['max_ulp']} | {m['max_rel']:.2e} |")
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    import jax

    # a wedged TPU tunnel blocks forever at device init; probe like bench.py
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from bench import _probe_once

    platform = jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", "")
    if (not platform or any(t in platform for t in ("tpu", "axon"))) and (
        not _probe_once("import jax; jax.devices()", 90.0)
    ):
        print("bench_accuracy: accelerator unreachable; certifying on CPU "
              "(pallas in interpret mode)", file=sys.stderr, flush=True)
        jax.config.update("jax_platforms", "cpu")

    on_accel = jax.default_backend() != "cpu"
    # full reduction length always (26304 = 3 calendar years of hourly steps
    # incl. the leap day, the headline shape); cells bounded off-chip
    cells = int(os.environ.get("FLOX_ACC_CELLS", 4096 if on_accel else 128))
    ntime = int(os.environ.get("FLOX_ACC_NTIME", 24 * (365 * 3 + 1)))
    seed = int(os.environ.get("FLOX_ACC_SEED", 0))

    rec = run(cells, ntime, seed)
    if args.json:
        print(json.dumps(rec))
    else:
        print(to_markdown(rec))


if __name__ == "__main__":
    main()
