"""Worked example: nD binning + large zonal statistics.

Covers the reference's user-stories/nD-bins.ipynb and
large-zonal-stats.ipynb workflows: (1) binning by two continuous
variables at once (the product grid comes back as one dim per grouper);
(2) county-style zonal means over a 2-D integer label map, with the
sparse-COO reindex for a huge id space.

Run from the repo root:

    PYTHONPATH=. python examples/nd_bins_zonal.py
"""

import numpy as np
import pandas as pd

from flox_tpu import groupby_reduce
from flox_tpu.reindex import reindex_sparse_coo


def nd_bins() -> None:
    # bin ocean temperature by (latitude band, salinity class) simultaneously
    rng = np.random.default_rng(0)
    n = 100_000
    lat = rng.uniform(-90, 90, n)
    salinity = rng.uniform(30, 40, n)
    temp = 20 - 0.2 * np.abs(lat) + rng.normal(0, 1, n)

    lat_bins = np.arange(-90, 91, 30)
    sal_bins = np.array([30.0, 34.0, 36.0, 40.0])
    mean_t, lat_iv, sal_iv = groupby_reduce(
        temp, lat, salinity,
        func="nanmean",
        expected_groups=(lat_bins, sal_bins),
        isbin=(True, True),
    )
    print("nD-binned mean shape:", np.asarray(mean_t).shape)  # (6, 3)
    print("lat bands:", lat_iv)
    print("warmest band mean:", float(np.nanmax(np.asarray(mean_t))))


def zonal_stats() -> None:
    # ~900 county labels over a 2-D grid (the reference's NWM workload
    # shape, asv_bench cohorts.py:84-97), reduced over both spatial dims
    rng = np.random.default_rng(1)
    ny, nx = 900, 1200
    county = rng.integers(0, 900, size=(ny, nx))
    runoff = rng.gamma(2.0, 1.5, size=(ny, nx))

    zonal_mean, county_ids = groupby_reduce(runoff, county, func="nanmean")
    print("zonal means:", np.asarray(zonal_mean).shape, "counties:", len(county_ids))

    # scatter the 900 found counties into the national 3.2M-id space without
    # densifying (reference reindex.py:106-157)
    national = reindex_sparse_coo(
        np.asarray(zonal_mean), pd.Index(county_ids), pd.RangeIndex(3_200_000),
        fill_value=0.0,
    )
    print("national sparse result:", national.shape, "stored:", national.data.size)


if __name__ == "__main__":
    nd_bins()
    zonal_stats()
