"""Worked example: day-of-year climatology over an array that never
materializes — the out-of-core streaming story.

The reference handles bigger-than-memory inputs by chunked runtimes (its
hourly-climatology user stories run on dask/cubed clusters). The TPU-native
equivalent is :func:`flox_tpu.streaming_groupby_reduce`: the array stays
behind a loader callable (zarr, memmap, a simulator...), slabs of the time
axis are placed on device one at a time, and dense per-group accumulators
merge on device — HBM holds one slab plus the (npix, 366) intermediates,
never the 40-year array.

Run from the repo root:

    PYTHONPATH=. python examples/streaming_bigger_than_memory.py

(on a machine without an accelerator: add JAX_PLATFORMS=cpu)
"""

import numpy as np

from flox_tpu import streaming_groupby_reduce


def main() -> None:
    # --- a 20-year daily "dataset" produced lazily, slab by slab -----------
    nyears, npix = 20, 512
    ndays = 365 * nyears
    doy = (np.arange(ndays) % 365).astype(np.int64)  # day-of-year labels

    def loader(start: int, stop: int) -> np.ndarray:
        """Synthesize columns [start, stop) on demand: an annual cycle plus
        deterministic 'weather'. Nothing outside this slab ever exists."""
        t = np.arange(start, stop)
        cycle = np.sin(2 * np.pi * (t % 365) / 365.0)[None, :]
        rng = np.random.default_rng(start)  # slab-local, reproducible
        noise = rng.normal(scale=0.3, size=(npix, stop - start))
        out = (cycle + noise).astype(np.float32)
        out[:, (t % 97) == 0] = np.nan  # sensor dropouts
        return out

    mean, doys = streaming_groupby_reduce(
        loader, doy, func="nanmean", batch_len=365,  # one year per slab
    )
    mean = np.asarray(mean)
    print(f"streamed {ndays} days in year-slabs -> climatology {mean.shape}")

    # --- verify against a host accumulation over the same loader -----------
    sums = np.zeros((npix, 365))
    cnts = np.zeros((npix, 365))
    for s in range(0, ndays, 365):
        slab = loader(s, s + 365).astype(np.float64)
        valid = ~np.isnan(slab)
        np.add.at(sums.T, doy[s : s + 365], np.where(valid, slab, 0.0).T)
        np.add.at(cnts.T, doy[s : s + 365], valid.T)
    expected = sums / cnts
    np.testing.assert_allclose(mean, expected, rtol=2e-6, atol=1e-7)
    print("matches the host oracle; max |dev| =",
          float(np.nanmax(np.abs(mean - expected))))

    # anomalies for one later year, using the streamed climatology
    year = loader(365 * 19, 365 * 20)
    anom = year - mean[:, doy[:365]].astype(np.float32)
    print("sample anomaly std:", float(np.nanstd(anom)))

    # --- round-5 capabilities on the same loader ---------------------------
    # 1. EXACT out-of-core median: the radix bisection consumes only
    #    per-group counts, so order statistics stream in nbits+1 passes
    #    over the loader (33 for f32 — the IO multiplier is the price;
    #    the reference's chunked quantile cannot do this at all)
    med, _ = streaming_groupby_reduce(
        loader, doy, func="nanmedian", batch_len=365
    )
    print("streamed EXACT median (33 passes):", np.asarray(med).shape)

    # 2. out-of-core grouped scan with the result streamed back out: a
    #    writer receives each scanned slab — nothing array-sized exists
    from flox_tpu import streaming_groupby_scan

    filled_std = []

    def writer(s: int, e: int, res: np.ndarray) -> None:
        filled_std.append(float(np.nanstd(res)))  # or: write to zarr[s:e]

    streaming_groupby_scan(
        loader, doy, func="ffill", batch_len=365, out=writer
    )
    print(f"streamed ffill through {len(filled_std)} slabs, loader in / writer out")

    # 3. with mesh= (a jax.sharding.Mesh), every slab scatters over the
    #    chips and the same calls become distributed: see
    #    docs/distributed.md "Streaming onto a mesh"


if __name__ == "__main__":
    main()
