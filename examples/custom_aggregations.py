"""Worked example: user-defined aggregations, eager and on the mesh.

The reference teaches this workflow in
docs/source/user-stories/custom-aggregations.ipynb: declare an
``Aggregation`` blueprint and run it through ``groupby_reduce`` like any
built-in. Here the same blueprint also runs distributed — the mesh
all-gathers each shard's dense intermediates and your ``combine`` callables
fold the stack.

Run from the repo root:

    PYTHONPATH=. python examples/custom_aggregations.py
"""

import numpy as np

import jax
import jax.numpy as jnp

from flox_tpu import Aggregation, groupby_reduce
from flox_tpu.parallel import make_mesh


# --- kernels with the engine plugin signature ------------------------------
# f(group_idx, array, *, axis, size, fill_value, dtype, **kw) -> (..., size)


def grouped_sumsq(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    from flox_tpu.kernels import generic_kernel

    a = jnp.asarray(array)
    return generic_kernel("nansum", group_idx, a * a, size=size, fill_value=0.0)


def grouped_count(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    from flox_tpu.kernels import generic_kernel

    return generic_kernel("nanlen", group_idx, array, size=size)


def main() -> None:
    # root-mean-square per group: stages = (sum of squares, count),
    # combine = sum each across shards, finalize = sqrt(ss / n)
    rms = Aggregation(
        "rms",
        numpy=(grouped_sumsq, grouped_count),  # eager stages
        chunk=(grouped_sumsq, grouped_count),  # per-shard stages
        combine=(lambda stacked: stacked.sum(0),  # (n_shards, ..., size) -> (..., size)
                 lambda stacked: stacked.sum(0)),
        finalize=lambda ss, n, **kw: (ss / n) ** 0.5,
        fill_value={"intermediate": (0.0, 0)},
        final_fill_value=np.nan,
    )

    rng = np.random.default_rng(0)
    n = 24 * 365
    month = ((np.arange(n) // (24 * 30.44)).astype(np.int64)) % 12
    signal = rng.normal(0.0, np.sqrt(1.0 + month), size=n)  # per-month spread

    eager, months = groupby_reduce(signal, month, func=rms)
    print("eager RMS per month:   ", np.round(np.asarray(eager), 3))

    mesh = make_mesh()  # all local devices
    dist, _ = groupby_reduce(signal, month, func=rms, method="map-reduce", mesh=mesh)
    print("mesh  RMS per month:   ", np.round(np.asarray(dist), 3))

    oracle = np.array([np.sqrt((signal[month == m] ** 2).mean()) for m in months])
    assert np.allclose(np.asarray(dist), oracle, rtol=1e-6)
    print("matches the per-group numpy oracle — expected ≈ sqrt(1+m):",
          np.round(np.sqrt(1.0 + np.arange(12)), 3))


if __name__ == "__main__":
    main()
