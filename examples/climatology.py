"""Worked example: ERA5-style monthly climatology + anomalies on TPU.

Run from the repo root (or after ``pip install -e .``):

    PYTHONPATH=. python examples/climatology.py

(on a machine without an accelerator: add JAX_PLATFORMS=cpu)
"""

import numpy as np

import jax
import jax.numpy as jnp

from flox_tpu import groupby_reduce, groupby_scan, groupby_reduce_device
from flox_tpu.parallel import make_mesh


def main() -> None:
    # --- synthetic ERA5-ish data: 3 years hourly on a coarse grid ----------
    rng = np.random.default_rng(0)
    ntime = 24 * 365 * 3
    nspace = 48 * 96
    month = ((np.arange(ntime) // (24 * 30.44)).astype(np.int64)) % 12
    data = rng.normal(280.0, 15.0, size=(nspace, ntime)).astype(np.float32)

    # --- 1. eager climatology on the local device --------------------------
    clim, months = groupby_reduce(data, month, func="nanmean")
    print("climatology:", np.asarray(clim).shape, "months:", months)

    # --- 2. the same reduction as one SPMD program over every device -------
    mesh = make_mesh()
    clim_d, _ = groupby_reduce(data, month, func="nanmean", method="map-reduce", mesh=mesh)
    print("distributed == eager:", np.allclose(np.asarray(clim_d), np.asarray(clim), rtol=1e-5))

    # --- 3. variability per month (collective Chan merge) ------------------
    var_d, _ = groupby_reduce(
        data, month, func="nanvar", method="cohorts", mesh=mesh, finalize_kwargs={"ddof": 1}
    )
    print("monthly variance:", np.asarray(var_d)[0, :3])

    # --- 4. grouped running means inside a user training step --------------
    months_dev = jnp.asarray(month)

    @jax.jit
    def anomaly_loss(x):
        c = groupby_reduce_device(x, months_dev, func="nanmean", expected_values=jnp.arange(12))
        return jnp.mean((x - c[..., months_dev]) ** 2)

    loss = anomaly_loss(jnp.asarray(data[:64]))
    grad = jax.grad(anomaly_loss)(jnp.asarray(data[:64]))
    print("loss:", float(loss), "grad finite:", bool(jnp.isfinite(grad).all()))

    # --- 5. grouped cumulative rainfall-style scan -------------------------
    running = groupby_scan(data[0], month, func="nancumsum")
    print("running sums:", np.asarray(running)[:4])


if __name__ == "__main__":
    main()
