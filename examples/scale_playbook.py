"""Worked example: the scale knobs — huge label spaces, order-statistics
lowerings, accumulation accuracy, datetime streaming, and distributed
order statistics.

Five short tours of the policy surface that distinguishes a million-group
zonal-statistics job from a 12-group climatology:

1. a 1,000,000-label reduction that exceeds the dense-intermediate HBM
   ceiling and auto-routes to the blocked owner-by-owner mesh program;
2. the two order-statistics lowerings (two-key sort vs MXU radix-select)
   returning bit-identical quantiles;
3. the Pallas accumulation disciplines (plain/kahan/dd) and what they buy
   at a 3-year reduction length;
4. NaT-aware datetime streaming through a loader;
5. median under method="map-reduce" on a mesh — the counting passes psum,
   so no shard needs a whole group (the reference forces blockwise).

Run from the repo root:

    PYTHONPATH=. python examples/scale_playbook.py

(on a machine without an accelerator: add JAX_PLATFORMS=cpu; to see the
multi-shard tours on CPU, also
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_ENABLE_X64=1 —
the oracle comparisons are f64-tight)
"""

import numpy as np

import flox_tpu
from flox_tpu import groupby_reduce, streaming_groupby_reduce


def huge_label_space() -> None:
    # county/catchment-style zonal statistics: 10^6 possible zones. The
    # dense (..., size) intermediates would dominate HBM, so the mesh
    # program is blocked by group ownership: every intermediate is
    # (..., size/ndev) from the start and one psum per owner block carries
    # the combine. Forcing a small ceiling here makes the routing visible
    # on any machine; real ceilings default to 8 GiB.
    import jax

    size = 1_000_000
    rng = np.random.default_rng(0)
    zones = rng.integers(0, size, 20_000)
    runoff = rng.gamma(2.0, 1.0, 20_000)
    if len(jax.devices()) == 1:
        # one device: the same ceiling produces the actionable guard
        # instead of an HBM OOM — run under an 8-device mesh (e.g.
        # XLA_FLAGS=--xla_force_host_platform_device_count=8) to see the
        # blocked program execute
        try:
            with flox_tpu.set_options(dense_intermediate_bytes_max=2**20):
                groupby_reduce(
                    runoff, zones, func="sum", expected_groups=np.arange(size),
                    method="map-reduce",
                )
        except ValueError as exc:
            print(f"single device: guard raised as designed —\n  {exc}\n")
        return
    with flox_tpu.set_options(dense_intermediate_bytes_max=12 * 2**20):
        totals, _ = groupby_reduce(
            runoff, zones, func="sum", expected_groups=np.arange(size),
            method="map-reduce",
        )
    dense = np.bincount(zones, weights=runoff, minlength=size)
    # f64-tight only when x64 is on; x32 configs still demonstrate the
    # routing, at f32 accuracy
    rtol = 1e-10 if jax.config.jax_enable_x64 else 1e-4
    np.testing.assert_allclose(np.asarray(totals), dense, rtol=rtol, atol=1e-6)
    print(f"blocked owner-by-owner: {size:,} zones reduced sharded, "
          f"{int((dense > 0).sum()):,} non-empty")


def order_statistics() -> None:
    # the same grouped quantile through both lowerings — identical bits.
    # On TPU, `select` replaces the sort with ~32 segment-sum counting
    # passes on the MXU; `bench.py` measures both and `auto` follows.
    rng = np.random.default_rng(1)
    codes = rng.integers(0, 12, 50_000)
    data = rng.normal(size=50_000).astype(np.float32)
    q90_sort, _ = groupby_reduce(
        data, codes, func="quantile", engine="jax", finalize_kwargs={"q": 0.9}
    )
    with flox_tpu.set_options(quantile_impl="select"):
        q90_sel, _ = groupby_reduce(
            data, codes, func="quantile", engine="jax", finalize_kwargs={"q": 0.9}
        )
    assert (np.asarray(q90_sort) == np.asarray(q90_sel)).all()
    print("order statistics: sort and radix-select lowerings agree bit-for-bit")


def accumulation_accuracy() -> None:
    # f32 running sums drift over a 3-year hourly reduction; the Pallas
    # kernel's kahan/dd disciplines recover the lost bits (measured table:
    # docs/engines.md). dd lands on the correctly-rounded f32 of the exact
    # f64 sum.
    from flox_tpu.pallas_kernels import segment_sum_pallas

    rng = np.random.default_rng(2)
    n = 26304  # 3 years of hourly steps
    data = (280.0 + 10.0 * rng.standard_normal((n, 1))).astype(np.float32)
    codes = np.zeros(n, dtype=np.int32)
    oracle = float(data.astype(np.float64).sum())
    for accum in ("plain", "kahan", "dd"):
        got = float(np.asarray(
            segment_sum_pallas(data, codes, 1, interpret=True, accum=accum)
        )[0, 0])
        ulps = abs(got - oracle) / np.spacing(np.float32(oracle))
        print(f"  accum={accum:5s}: {ulps:5.1f} f32 ULPs from the f64 oracle")


def datetime_streaming() -> None:
    # last-observation timestamps per station, streamed from a "store"
    # with NaT gaps — the int64 NaT channel rides the slab merges
    import jax

    if not jax.config.jax_enable_x64:
        print("datetime streaming: skipped (needs JAX_ENABLE_X64=1 — int64 "
              "NaT sentinels do not survive the int32 downcast)")
        return
    rng = np.random.default_rng(3)
    n = 30_000
    stations = rng.integers(0, 50, n)
    stamps = (
        np.datetime64("2024-01-01", "ns")
        + rng.integers(0, 10**15, n).astype("timedelta64[ns]")
    )
    stamps[rng.random(n) < 0.1] = np.datetime64("NaT")
    last, _ = streaming_groupby_reduce(
        lambda s, e: stamps[s:e], stations, func="nanlast", batch_len=4096
    )
    eager, _ = groupby_reduce(stamps, stations, func="nanlast")
    np.testing.assert_array_equal(np.asarray(last), np.asarray(eager))
    print(f"datetime streaming: last timestamps for 50 stations, e.g. "
          f"{np.asarray(last)[0]}")


def distributed_order_statistics() -> None:
    # quantile/median run method="map-reduce" on a mesh: the radix-select
    # counting passes psum across shards, so no shard needs a whole group
    # (the reference forces blockwise for order statistics). Bit-identical
    # to eager — the value reconstructs from GLOBAL counts.
    from flox_tpu.parallel import make_mesh

    rng = np.random.default_rng(4)
    codes = rng.integers(0, 12, 50_000)
    data = rng.normal(size=50_000).astype(np.float32)
    eager, _ = groupby_reduce(data, codes, func="nanmedian")
    sharded, _ = groupby_reduce(
        data, codes, func="nanmedian", method="map-reduce", mesh=make_mesh()
    )
    assert (np.asarray(eager) == np.asarray(sharded)).all()
    print("distributed median: map-reduce on the mesh, bit-identical to eager")


def main() -> None:
    huge_label_space()
    order_statistics()
    accumulation_accuracy()
    datetime_streaming()
    distributed_order_statistics()


if __name__ == "__main__":
    main()
